"""Learned cost model behind every lane gate and pacing decision (r22).

The engine carries ~a dozen hand-tuned thresholds (``SORTED_MIN_ROWS``,
``device_join_min_rows``, ``staging_codec_min_ratio``, hedge
quantile/delay, the MIMD controller steps) that are all
provisional-on-CPU — yet the r15 attribution plane already records
everything needed to learn them: ``device_programs`` rows carry XLA
cost_analysis flops/bytes, ``device_dispatches`` carries measured wall
time per program key, and the r11 fold-latency view is consulted for
hedging. This module closes that loop with ONE model:

  observation   every device dispatch (whole-offload fold, stream fold,
                stream window, batched fold, device join) and the host
                join feed ``observe(sig, rows, wall_s)`` — a bounded
                per-(program-family, pow2 rows bucket) reservoir of wall
                seconds plus a per-family rows/s throughput reservoir
                (deque eviction = natural decay toward recent behavior).
  prediction    ``predict_seconds`` answers from the bucket reservoir
                when the exact shape has been seen, falls back to the
                family throughput for unseen shapes of a known family,
                and bottoms out in a roofline prior — cost_analysis
                flops/bytes x device flop/byte rates calibrated online
                from the SAME dispatches — for never-seen programs.
                ``None`` means "no opinion": the caller keeps its
                hand-tuned heuristic EXACTLY, so cold-start and
                flag-off behavior are bit-for-bit the pre-r22 engine.
  decision      the lane gates consult ``choose_*`` helpers that return
                the heuristic default unless the model has at least
                ``cost_model_min_samples`` observations on BOTH sides,
                and every flip is clamped to hard rails derived from
                the hand-tuned flag (``cost_model_rail_factor``) — the
                flags stop being the answer but remain the fence.

Every routed decision picks between bit-identical lanes (sort-compact
vs direct scatter, device vs host join, codec vs raw wire), so the
model changes only speed, never answers.

Shadow mode (``cost_model_shadow``): predictions and decisions are
computed and recorded (``shadow_snapshot``) but never actuated — the
heuristic path runs while the model's would-be choices and its
prediction error (``error_snapshot``) accumulate for offline review.

Persistence: the full reservoir state serializes as one JSON blob under
``costmodel/state`` in a vizier datastore (``attach_datastore``), the
FoldSignatureStore posture — advisory, never raises — so calibration
survives restarts with zero re-learning.

Design contract (mirrors utils/faults.py and parallel/profiler.py):
call sites gate on the module-level ``ACTIVE`` bool — disabled, every
hook is one attribute load + branch, held <1% by
tools/microbench_fault_overhead.py's ``cost_model_overhead`` key.
"""

from __future__ import annotations

import collections
import json
import logging
import threading
from typing import Optional

from pixie_tpu.utils.config import define_flag, flags

_log = logging.getLogger("pixie_tpu.serving")

define_flag(
    "cost_model",
    True,
    help_="Route lane gates, hedge delay, admission estimates, and the "
    "controller through the learned CostModel (r22). Off, every "
    "decision falls back to its hand-tuned flag exactly (pre-r22 "
    "behavior); the flags always remain hard rails either way.",
)
define_flag(
    "cost_model_shadow",
    False,
    help_="Shadow mode: the CostModel observes dispatches and records "
    "its would-be decisions and prediction error, but never actuates — "
    "every gate runs its hand-tuned heuristic.",
)
define_flag(
    "cost_model_min_samples",
    3,
    help_="Observations required per (family, bucket) reservoir before "
    "the model voices an opinion; below it, predict_seconds falls "
    "through to the next backoff rung (throughput, roofline, None).",
)
define_flag(
    "cost_model_rail_factor",
    8.0,
    help_="Hard-rail width around each hand-tuned flag: the model may "
    "move a gate threshold or pacing value at most this factor away "
    "from the configured flag in either direction.",
)
define_flag(
    "cost_model_reservoir",
    64,
    help_="Samples kept per (family, bucket) wall-time reservoir and "
    "per-family rate reservoir; deque eviction is the decay.",
)
define_flag(
    "cost_model_persist_every",
    64,
    help_="Observations between datastore snapshots of the model state "
    "(when a datastore is attached); 0 disables periodic persistence.",
)

# Fast gates, synced with the cost_model/cost_model_shadow flags: one
# attribute load + branch per call site when the model is off.
ACTIVE = False
SHADOW = False

_DS_KEY = "costmodel/state"
_STATE_VERSION = 1


def refresh() -> None:
    global ACTIVE, SHADOW
    SHADOW = bool(flags.cost_model_shadow)
    ACTIVE = bool(flags.cost_model) or SHADOW


def set_enabled(on: bool, shadow: bool = False) -> None:
    """Flip the model's observe/decide gates directly (tests, benches)."""
    global ACTIVE, SHADOW
    SHADOW = bool(shadow)
    ACTIVE = bool(on) or SHADOW


def family_of(sig: str) -> str:
    """Program-key family: the unit-kind prefix plus any lane tokens
    (``sortlane:``/``joinlane:``) — the identity that determines which
    physical lane ran, with the shape-specific remainder erased so
    observations pool across shapes of one lane."""
    parts = str(sig).split("|")
    fam = [parts[0]]
    fam += [
        p
        for p in parts[1:]
        if p.startswith("sortlane:") or p.startswith("joinlane:")
    ]
    return "|".join(fam)


def bucket_of(rows: int) -> int:
    """Pow2 shape bucket; 0 holds shapeless (whole-offload) costs."""
    r = int(rows)
    return r.bit_length() if r > 0 else 0


def _median(vals) -> Optional[float]:
    s = sorted(vals)
    n = len(s)
    if not n:
        return None
    m = n // 2
    return float(s[m]) if n % 2 else float((s[m - 1] + s[m]) / 2.0)


def _quantile(vals, q: float) -> Optional[float]:
    s = sorted(vals)
    if not s:
        return None
    idx = min(int(q * len(s)), len(s) - 1)
    return float(s[idx])


class CostModel:
    """Per-family cost reservoirs + calibrated roofline prior.

    All public methods are thread-safe and never raise: prediction is
    advisory, a broken model must never fail a query."""

    def __init__(self, cap: Optional[int] = None):
        self._lock = threading.RLock()
        self._cap = max(int(cap or flags.cost_model_reservoir), 4)
        # (family, bucket) -> deque[wall seconds]
        self._samples: dict = {}
        # family -> deque[units/s] (rows/s for folds+joins, bytes/s for
        # the stage|codec / stage|raw wire families)
        self._rates: dict = {}
        # family -> deque[relative prediction error] (predict-then-learn)
        self._errors: dict = {}
        # Calibrated device rates from cost_analysis-bearing dispatches.
        self._flop_rate: collections.deque = collections.deque(
            maxlen=self._cap
        )
        self._byte_rate: collections.deque = collections.deque(
            maxlen=self._cap
        )
        # Hedge plane: program_key -> deque[seconds] fed from the r11
        # fold-latency view (a smoothed, decayed per-key estimate).
        self._latency: dict = {}
        # Shadow decision log (site, default, model choice, evidence).
        self._shadow_log: collections.deque = collections.deque(maxlen=256)
        self._ds = None
        self._dirty = 0

    # -- reservoirs ----------------------------------------------------------
    def _deque(self, table: dict, key):
        d = table.get(key)
        if d is None:
            d = table[key] = collections.deque(maxlen=self._cap)
        return d

    def _min_samples(self) -> int:
        return max(int(flags.cost_model_min_samples), 1)

    def _rail(self) -> float:
        return max(float(flags.cost_model_rail_factor), 1.0)

    # -- observation ---------------------------------------------------------
    def observe(self, sig: str, rows: int, wall_s: float) -> None:
        """One measured dispatch. Predict-first: the pre-ingest
        prediction's relative error lands in the family error reservoir
        (this is the honest error — the sample has not yet influenced
        the model), then the sample is ingested and, when the r15
        program registry knows this sig's cost_analysis, the implied
        device flop/byte rates calibrate the roofline prior."""
        try:
            wall = float(wall_s)
            if wall <= 0.0:
                return
            fam = family_of(sig)
            with self._lock:
                pred = self._predict_locked(sig=sig, family=fam, rows=rows)
                if pred is not None:
                    self._deque(self._errors, fam).append(
                        abs(pred - wall) / wall
                    )
                self._ingest_locked(fam, rows, wall)
                self._calibrate_locked(sig, wall)
                self._maybe_persist_locked()
        except Exception:
            pass  # advisory: observation must never fail a dispatch

    def observe_family(self, family: str, rows: int, wall_s: float) -> None:
        """Like ``observe`` but for lanes without a program signature
        (the host join, the whole-offload breaker key)."""
        try:
            wall = float(wall_s)
            if wall <= 0.0:
                return
            with self._lock:
                pred = self._predict_locked(family=family, rows=rows)
                if pred is not None:
                    self._deque(self._errors, family).append(
                        abs(pred - wall) / wall
                    )
                self._ingest_locked(family, rows, wall)
                self._maybe_persist_locked()
        except Exception:
            pass

    def _ingest_locked(self, family: str, rows: int, wall: float) -> None:
        self._deque(self._samples, (family, bucket_of(rows))).append(wall)
        if rows > 0:
            self._deque(self._rates, family).append(rows / wall)
        self._dirty += 1

    def _calibrate_locked(self, sig: str, wall: float) -> None:
        cost = _program_cost(sig)
        if not cost:
            return
        flops = float(cost.get("flops", 0.0) or 0.0)
        nbytes = float(cost.get("bytes_accessed", 0.0) or 0.0)
        if flops > 0:
            self._flop_rate.append(flops / wall)
        if nbytes > 0:
            self._byte_rate.append(nbytes / wall)

    # -- prediction ----------------------------------------------------------
    def predict_seconds(
        self,
        sig: Optional[str] = None,
        family: Optional[str] = None,
        rows: int = 0,
    ) -> Optional[float]:
        """Backoff ladder: exact (family, bucket) reservoir median ->
        family throughput (rows / median rows-per-s) -> roofline prior
        (cost_analysis x calibrated rates, sig required) -> None."""
        try:
            with self._lock:
                return self._predict_locked(sig=sig, family=family, rows=rows)
        except Exception:
            return None

    def _predict_locked(
        self,
        sig: Optional[str] = None,
        family: Optional[str] = None,
        rows: int = 0,
    ) -> Optional[float]:
        fam = family or (family_of(sig) if sig else None)
        need = self._min_samples()
        if fam is not None:
            d = self._samples.get((fam, bucket_of(rows)))
            if d is not None and len(d) >= need:
                return _median(d)
            if rows > 0:
                r = self._rates.get(fam)
                if r is not None and len(r) >= need:
                    rate = _median(r)
                    if rate and rate > 0:
                        return rows / rate
        if sig is not None:
            return self._roofline_locked(sig)
        return None

    def _roofline_locked(self, sig: str) -> Optional[float]:
        cost = _program_cost(sig)
        if not cost:
            return None
        need = self._min_samples()
        flops = float(cost.get("flops", 0.0) or 0.0)
        nbytes = float(cost.get("bytes_accessed", 0.0) or 0.0)
        est = []
        if flops > 0 and len(self._flop_rate) >= need:
            fr = _median(self._flop_rate)
            if fr and fr > 0:
                est.append(flops / fr)
        if nbytes > 0 and len(self._byte_rate) >= need:
            br = _median(self._byte_rate)
            if br and br > 0:
                est.append(nbytes / br)
        return max(est) if est else None

    def pooled_rate(self, kinds=("fold", "bfold", "stream_fold")) -> (
        Optional[float]
    ):
        """Median units/s pooled across every family whose kind prefix
        is in ``kinds`` (cross-lane generalization for callers that
        know a size but not which lane will run)."""
        try:
            with self._lock:
                pool = []
                for fam, d in self._rates.items():
                    if fam.split("|", 1)[0] in kinds:
                        pool.extend(d)
                if len(pool) < self._min_samples():
                    return None
                return _median(pool)
        except Exception:
            return None

    # -- decisions (each returns the heuristic default unless evidence
    # -- clears min_samples on both sides AND the flip stays inside the
    # -- rails; shadow mode records the would-be choice and defers) ----------
    def _shadow_record(self, site: str, default, choice, **ev) -> None:
        self._shadow_log.append(
            dict(site=site, default=default, choice=choice, **ev)
        )

    def choose_sorted_lane(
        self, n_rows: int, nseg: Optional[int], default: bool, min_rows: int
    ) -> bool:
        """r8 sort-compact vs direct-scatter lane (ops/segment.py).
        Rails: a lane choice is equivalent to moving the ``min_rows``
        threshold, and the model may move it at most ``rail_factor``
        from the hand-tuned value in either direction — below
        ``min_rows / rail`` the sorted lane is refused, at or above
        ``min_rows * rail`` it is forced, and the compacted-scatter
        structural guard (nseg*4 > n_rows) stays hard everywhere. Both
        lanes are bit-identical (test-pinned), so a flip changes only
        speed."""
        try:
            p1 = self.predict_seconds(
                family="fold|sortlane:1", rows=n_rows
            )
            p0 = self.predict_seconds(
                family="fold|sortlane:0", rows=n_rows
            )
            if p1 is None or p0 is None:
                return default
            choice = p1 < p0
            rail = self._rail()
            if n_rows >= int(min_rows * rail):
                choice = True  # rail: the flag decides far above it
            if choice and n_rows < int(min_rows / rail):
                choice = False  # rail: never sort far below the flag
            if choice and nseg is not None and nseg * 4 > n_rows:
                choice = False  # structural guard stays hard
            if SHADOW:
                self._shadow_record(
                    "sorted_lane", default, choice, n_rows=int(n_rows),
                    pred_sorted_s=p1, pred_direct_s=p0,
                )
                return default
            return choice
        except Exception:
            return default

    def choose_device_join(self, total_rows: int, default: bool) -> bool:
        """r19 device sort-merge vs host EquijoinNode gate
        (``device_join_min_rows``). True = device. Rails: the model may
        move the effective threshold at most ``rail_factor`` from the
        flag in either direction — never device below
        ``device_join_min_rows / rail`` rows, always device at or above
        ``device_join_min_rows * rail`` (so a test or operator pinning
        the flag to 0 forces the device lane exactly as pre-r22)."""
        try:
            pd = self.predict_seconds(
                family="join|joinlane:sort_merge", rows=total_rows
            )
            ph = self.predict_seconds(family="join|host", rows=total_rows)
            if pd is None or ph is None:
                return default
            choice = pd < ph
            rail = self._rail()
            flag_rows = int(flags.device_join_min_rows)
            if total_rows >= int(flag_rows * rail):
                choice = True
            if choice and total_rows < int(flag_rows / rail):
                choice = False
            if SHADOW:
                self._shadow_record(
                    "device_join", default, choice,
                    total_rows=int(total_rows),
                    pred_device_s=pd, pred_host_s=ph,
                )
                return default
            return choice
        except Exception:
            return default

    def codec_min_ratio(self) -> float:
        """Effective ``staging_codec_min_ratio``: the flag scaled by the
        measured codec-vs-raw seconds-per-staged-byte ratio (codec lane
        cheaper per byte -> lower bar -> encode more), clamped to
        [max(1, flag/rail), flag*rail]. Cold or shadow: the flag,
        exactly. Either lane decodes bit-identically, so this moves
        only wire bytes and seconds."""
        base = float(flags.staging_codec_min_ratio)
        try:
            need = self._min_samples()
            with self._lock:
                rc = self._rates.get("stage|codec")
                rr = self._rates.get("stage|raw")
                if (
                    rc is None or rr is None
                    or len(rc) < need or len(rr) < need
                ):
                    return base
                codec_bps = _median(rc)
                raw_bps = _median(rr)
            if not codec_bps or not raw_bps:
                return base
            # seconds/byte ratio == inverse bytes/s ratio
            eff = base * (raw_bps / codec_bps)
            rail = self._rail()
            eff = min(max(eff, max(1.0, base / rail)), base * rail)
            if SHADOW:
                self._shadow_record(
                    "codec_min_ratio", base, eff,
                    codec_bytes_per_s=codec_bps, raw_bytes_per_s=raw_bps,
                )
                return base
            return eff
        except Exception:
            return base

    def hedge_delay_s(
        self, program_keys, view: dict, q_key: str, raw_s: Optional[float]
    ) -> Optional[float]:
        """r17 hedge pacing: ingest the instantaneous fold-latency view
        into decayed per-program-key reservoirs and answer with the
        smoothed median of the relevant keys, clamped to
        [raw/rail, raw*rail] around the instantaneous value the r17
        heuristic would have used. ``None`` = defer to the caller's
        raw value (cold) — and no data at all still means no hedge."""
        try:
            with self._lock:
                vals = []
                for pk in program_keys:
                    d = self._deque(self._latency, str(pk))
                    for st in (view.get(pk) or {}).values():
                        v = st.get(q_key)
                        if v:
                            d.append(float(v) / 1e3)
                    if len(d) >= self._min_samples():
                        m = _median(d)
                        if m:
                            vals.append(m)
                self._dirty += 1
                self._maybe_persist_locked()
            if not vals:
                return None
            pred = max(vals)
            if raw_s is not None and raw_s > 0:
                rail = self._rail()
                pred = min(max(pred, raw_s / rail), raw_s * rail)
            if SHADOW:
                self._shadow_record(
                    "hedge_delay", raw_s, pred, q_key=q_key
                )
                return None
            return pred
        except Exception:
            return None

    def estimate_fold_seconds(self, rows: int) -> Optional[float]:
        """Admission advisory: predicted fold seconds for a query
        touching ``rows`` staged rows, from the pooled fold-lane
        throughput. None cold — the bytes-only admission check (which
        this never replaces) carries alone."""
        if rows <= 0:
            return None
        rate = self.pooled_rate()
        return rows / rate if rate else None

    def estimate_seconds_for_bytes(self, nbytes: int) -> Optional[float]:
        """Predicted staging seconds for ``nbytes`` staged bytes from
        the wire-lane byte rates (codec and raw pooled)."""
        if nbytes <= 0:
            return None
        rate = self.pooled_rate(kinds=("stage",))
        return nbytes / rate if rate else None

    def fold_seconds_p50(self) -> Optional[float]:
        """Controller-facing: median whole-offload fold seconds (the
        shapeless bucket-0 reservoir of the ``fold`` family)."""
        try:
            with self._lock:
                d = self._samples.get(("fold", 0))
                if d is None or len(d) < self._min_samples():
                    return None
                return _median(d)
        except Exception:
            return None

    def controller_predicted_wait_ms(
        self, queue_depth: int, concurrent: int
    ) -> Optional[float]:
        """r16 controller upgrade: predicted time-in-queue for the
        backlog — queue_depth folds at the learned per-fold median,
        drained ``concurrent`` at a time. The controller raises
        concurrency when THIS exceeds the wait target, before the
        reactive windowed quantile has even seen the slow folds. None
        cold (pure-MIMD, pre-r22); shadow records and defers."""
        if queue_depth <= 0:
            return None
        s = self.fold_seconds_p50()
        if s is None:
            return None
        pred = queue_depth * s * 1e3 / max(int(concurrent), 1)
        if SHADOW:
            self._shadow_record(
                "controller_wait", None, pred,
                queue_depth=int(queue_depth), concurrent=int(concurrent),
            )
            return None
        return pred

    def placement_latency_ms(self) -> Optional[float]:
        """r18 placement: a model-predicted default per-fold latency for
        agents the latency view has not measured yet, so a known-cost
        workload ranks them on the ``latency_fallback`` rung instead of
        ``cold``. None cold (pre-r22 ladder exactly)."""
        s = self.fold_seconds_p50()
        return s * 1e3 if s is not None else None

    # -- introspection -------------------------------------------------------
    def error_snapshot(self) -> dict:
        """Per-family prediction-error quantiles (relative error of the
        predict-before-ingest estimate vs the measured wall)."""
        with self._lock:
            out = {}
            for fam, d in self._errors.items():
                if not d:
                    continue
                out[fam] = {
                    "n": len(d),
                    "p50": round(_quantile(d, 0.5), 4),
                    "p90": round(_quantile(d, 0.9), 4),
                }
            return out

    def shadow_snapshot(self) -> list:
        with self._lock:
            return [dict(e) for e in self._shadow_log]

    def sample_counts(self) -> dict:
        with self._lock:
            return {
                f"{fam}@{b}": len(d)
                for (fam, b), d in self._samples.items()
            }

    # -- persistence (FoldSignatureStore posture: advisory, never raises) ----
    def attach_datastore(self, ds) -> None:
        """Load any persisted state, then snapshot every
        ``cost_model_persist_every`` observations."""
        self._ds = ds
        self.load(ds)

    def state(self) -> dict:
        with self._lock:
            return {
                "v": _STATE_VERSION,
                "samples": {
                    f"{fam}\t{b}": list(d)
                    for (fam, b), d in self._samples.items()
                },
                "rates": {f: list(d) for f, d in self._rates.items()},
                "errors": {f: list(d) for f, d in self._errors.items()},
                "flop_rate": list(self._flop_rate),
                "byte_rate": list(self._byte_rate),
                "latency": {k: list(d) for k, d in self._latency.items()},
            }

    def load_state(self, st: dict) -> None:
        def _dq(vals):
            return collections.deque(
                [float(v) for v in vals], maxlen=self._cap
            )

        with self._lock:
            self._samples = {}
            for key, vals in (st.get("samples") or {}).items():
                fam, _, b = key.rpartition("\t")
                self._samples[(fam, int(b))] = _dq(vals)
            self._rates = {
                f: _dq(v) for f, v in (st.get("rates") or {}).items()
            }
            self._errors = {
                f: _dq(v) for f, v in (st.get("errors") or {}).items()
            }
            self._flop_rate = _dq(st.get("flop_rate") or [])
            self._byte_rate = _dq(st.get("byte_rate") or [])
            self._latency = {
                k: _dq(v) for k, v in (st.get("latency") or {}).items()
            }
            self._dirty = 0

    def save(self, ds=None) -> bool:
        if ds is None:
            ds = self._ds
        if ds is None:
            return False
        try:
            blob = json.dumps(self.state(), sort_keys=True).encode()
            ds.set(_DS_KEY, blob)
            with self._lock:
                self._dirty = 0
            return True
        except Exception:
            _log.warning("cost-model persist failed (ignored)", exc_info=True)
            return False

    def load(self, ds=None) -> bool:
        if ds is None:
            ds = self._ds
        if ds is None:
            return False
        try:
            raw = ds.get(_DS_KEY)
            if not raw:
                return False
            st = json.loads(raw.decode())
            if int(st.get("v", 0)) != _STATE_VERSION:
                return False
            self.load_state(st)
            return True
        except Exception:
            _log.warning("cost-model load failed (ignored)", exc_info=True)
            return False

    def _maybe_persist_locked(self) -> None:
        every = int(flags.cost_model_persist_every)
        if self._ds is None or every <= 0 or self._dirty < every:
            return
        # Snapshot outside the request path would be nicer; the blob is
        # a few KB and the datastore write is advisory, so inline is
        # fine at this cadence.
        self._dirty = 0
        try:
            blob = json.dumps(self.state(), sort_keys=True).encode()
            self._ds.set(_DS_KEY, blob)
        except Exception:
            pass


def _program_cost(sig: str) -> Optional[dict]:
    """r15 program-registry row for ``sig`` (flops/bytes_accessed), or
    None. Lazy import: profiler lives in the parallel package, whose
    __init__ pulls the full pipeline — resolving it at call time keeps
    this module import-light (config only)."""
    try:
        from pixie_tpu.parallel import profiler

        return profiler.program_cost(sig)
    except Exception:
        return None


# -- module-level singleton + forwarding call sites --------------------------
MODEL = CostModel()


def model() -> CostModel:
    return MODEL


def reset() -> None:
    """Fresh model + gates resynced from flags (tests)."""
    global MODEL
    MODEL = CostModel()
    refresh()


def observe(sig: str, rows: int, wall_s: float) -> None:
    MODEL.observe(sig, rows, wall_s)


def observe_family(family: str, rows: int, wall_s: float) -> None:
    MODEL.observe_family(family, rows, wall_s)


def predict_seconds(sig=None, family=None, rows: int = 0):
    return MODEL.predict_seconds(sig=sig, family=family, rows=rows)


def choose_sorted_lane(n_rows, nseg, default, min_rows) -> bool:
    return MODEL.choose_sorted_lane(n_rows, nseg, default, min_rows)


def choose_device_join(total_rows, default) -> bool:
    return MODEL.choose_device_join(total_rows, default)


def codec_min_ratio() -> float:
    return MODEL.codec_min_ratio()


def hedge_delay_s(program_keys, view, q_key, raw_s):
    return MODEL.hedge_delay_s(program_keys, view, q_key, raw_s)


def estimate_fold_seconds(rows: int):
    return MODEL.estimate_fold_seconds(rows)


def estimate_seconds_for_bytes(nbytes: int):
    return MODEL.estimate_seconds_for_bytes(nbytes)


def fold_seconds_p50():
    return MODEL.fold_seconds_p50()


def controller_predicted_wait_ms(queue_depth: int, concurrent: int):
    return MODEL.controller_predicted_wait_ms(queue_depth, concurrent)


def placement_latency_ms():
    return MODEL.placement_latency_ms()


def error_snapshot() -> dict:
    return MODEL.error_snapshot()


def shadow_snapshot() -> list:
    return MODEL.shadow_snapshot()


def attach_datastore(ds) -> None:
    MODEL.attach_datastore(ds)


def save(ds=None) -> bool:
    return MODEL.save(ds)


refresh()
