"""Broker admission control: concurrency limit + weighted fair queueing.

Ref posture: the reference's query broker accepts every ExecuteScript and
lets timeouts sort out overload; a broker serving heavy traffic needs a
front door. This controller gives ``QueryBroker.execute_script`` one:

- **Concurrency limit.** At most ``admission_max_concurrent`` queries
  execute at once; arrivals past that wait in a bounded queue
  (``admission_max_queue``) and past THAT are rejected immediately with
  a structured ``AdmissionRejected`` — overload degrades into fast
  errors, never into unbounded memory or a hang.
- **Per-tenant weighted fair queueing.** Waiters are granted in
  virtual-finish-time order: a tenant's request is stamped
  ``max(vclock, tenant_last) + 1/weight``, so a tenant's own backlog
  accrues virtual time linearly while a quiet tenant's first request
  lands just after the clock — a starved tenant schedules ahead of a
  heavy tenant's backlog tail, and a 2x-weighted tenant drains twice as
  fast under contention (classic WFQ/SFQ virtual-clock scheduling).
- **HBM byte-budget check.** Before admitting, the controller consults
  the residency pool (when wired): if PINNED bytes already exceed the
  budget, no eviction can make room for this query's staging — reject
  with ``reason="hbm_budget"`` instead of letting it OOM the device.
- **Observability.** Queue depth / active gauges, a wait-time histogram
  (the r11 Histogram kind), and per-reason rejection counters on the
  shared /metrics registry; ``snapshot()`` feeds the broker's /statusz
  (the r10 health plane).

Fault site ``serving.admission_reject`` forces a rejection so chaos
tests can prove the structured-error path end to end.
"""

from __future__ import annotations

import heapq
import itertools
import threading
import time
from typing import Callable, Optional

from pixie_tpu.utils import faults, flags, metrics_registry

_M = metrics_registry()
_QUEUE_DEPTH = _M.gauge(
    "admission_queue_depth", "Queries waiting in the admission queue."
)
_ACTIVE = _M.gauge(
    "admission_active", "Queries currently admitted and executing."
)
_ADMITTED = _M.counter(
    "admission_admitted_total", "Queries admitted, by tenant."
)
_REJECTED = _M.counter(
    "admission_rejected_total",
    "Queries rejected, by reason and tenant (r15: per-tenant SLO rules "
    "get native series; sum across tenants via Counter.total).",
)
_WAIT_SECONDS = _M.histogram(
    "admission_wait_seconds",
    "Time a query spent in the admission queue before grant/rejection, "
    "by tenant (aggregate views read Histogram.agg_quantile).",
)
_LOCK_WAIT = _M.histogram(
    "admission_lock_wait_seconds",
    "Time a caller waited to acquire the admission controller's lock "
    "(only contended acquisitions are observed — the r12 follow-on "
    "lock-profiling signal at ~1k-client depth).",
)


class AdmissionRejected(RuntimeError):
    """Structured overload rejection: carries enough for a client to
    back off intelligently (reason, tenant, live queue depth, how long
    the request waited)."""

    def __init__(
        self,
        tenant: str,
        reason: str,
        queue_depth: int = 0,
        waited_s: float = 0.0,
        detail: str = "",
    ):
        super().__init__(
            f"admission rejected for tenant {tenant!r}: {reason}"
            + (f" ({detail})" if detail else "")
            + f" [queue_depth={queue_depth}, waited={waited_s:.3f}s]"
        )
        self.tenant = tenant
        self.reason = reason
        self.queue_depth = queue_depth
        self.waited_s = waited_s
        self.detail = detail

    def to_dict(self) -> dict:
        return {
            "tenant": self.tenant,
            "reason": self.reason,
            "queue_depth": self.queue_depth,
            "waited_s": round(self.waited_s, 6),
            "detail": self.detail,
        }


def parse_tenant_weights(spec: str) -> dict[str, float]:
    """'tenant:weight,tenant:weight' -> {tenant: weight}; malformed
    entries are skipped (a typo'd weight must not take the broker down)."""
    out: dict[str, float] = {}
    for entry in (spec or "").split(","):
        entry = entry.strip()
        if not entry:
            continue
        name, _, w = entry.rpartition(":")
        try:
            weight = float(w)
        except ValueError:
            continue
        if name and weight > 0:
            out[name] = weight
    return out


class _Waiter:
    __slots__ = ("vtime", "seq", "tenant", "granted", "abandoned")

    def __init__(self, vtime: float, seq: int, tenant: str):
        self.vtime = vtime
        self.seq = seq
        self.tenant = tenant
        self.granted = False
        self.abandoned = False  # timed out: skip when popped

    def __lt__(self, other: "_Waiter") -> bool:
        return (self.vtime, self.seq) < (other.vtime, other.seq)


class _Ticket:
    """Held by an admitted query; release() frees the slot (idempotent).
    Usable as a context manager."""

    def __init__(
        self,
        ctl: "AdmissionController",
        tenant: str,
        waited_s,
        estimated_seconds: float = 0.0,
    ):
        self._ctl = ctl
        self.tenant = tenant
        self.waited_s = waited_s
        # r22 advisory: the cost model's predicted fold seconds for this
        # query at admission time (0 when the model was cold/off).
        self.estimated_seconds = float(estimated_seconds)
        self._released = False

    def release(self) -> None:
        if not self._released:
            self._released = True
            self._ctl._release(self.estimated_seconds)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.release()
        return False


class AdmissionController:
    def __init__(
        self,
        max_concurrent: Optional[int] = None,
        max_queue: Optional[int] = None,
        timeout_s: Optional[float] = None,
        tenant_weights: Optional[dict[str, float]] = None,
        budget_fn: Optional[Callable[[], dict]] = None,
    ):
        """Unset limits re-read their flags per call, so runtime flag
        flips apply live. ``budget_fn`` returns a residency snapshot
        (ResidencyPool.snapshot-shaped: pinned_bytes/budget_bytes)."""
        self._max_concurrent = max_concurrent
        self._max_queue = max_queue
        self._timeout_s = timeout_s
        self._weights = tenant_weights
        self._budget_fn = budget_fn
        self._cv = threading.Condition()
        self._active = 0
        self._heap: list[_Waiter] = []
        self._waiting = 0
        self._vclock = 0.0
        self._tenant_vtime: dict[str, float] = {}
        self._seq = itertools.count()
        # r22: sum of the cost model's predicted fold seconds across
        # admitted (unreleased) queries — a predicted-backlog signal for
        # /statusz and the controller, never a rejection input.
        self._inflight_seconds = 0.0

    # -- limits (flag-backed unless pinned at construction) ------------------
    def _limit(self) -> int:
        return (
            self._max_concurrent
            if self._max_concurrent is not None
            else max(int(flags.admission_max_concurrent), 1)
        )

    def _queue_cap(self) -> int:
        return (
            self._max_queue
            if self._max_queue is not None
            else max(int(flags.admission_max_queue), 0)
        )

    def _timeout(self) -> float:
        return (
            self._timeout_s
            if self._timeout_s is not None
            else float(flags.admission_timeout_s)
        )

    def _weight(self, tenant: str) -> float:
        weights = (
            self._weights
            if self._weights is not None
            else parse_tenant_weights(flags.admission_tenant_weights)
        )
        return float(weights.get(tenant, 1.0))

    # -- the front door ------------------------------------------------------
    def acquire(
        self,
        tenant: str = "default",
        estimated_bytes: int = 0,
        estimated_seconds: float = 0.0,
    ) -> _Ticket:
        """Block until admitted (WFQ order) or raise AdmissionRejected.
        Every exit path is bounded: queue-full and budget rejections are
        immediate, a queued request rejects at ``admission_timeout_s``.

        ``estimated_bytes`` (r13): the query's predicted staging
        footprint from table metadata (row count × encoded column
        widths — see ``estimate_staging_bytes``). When set, the HBM
        budget check rejects a query whose staging could never fit
        even after evicting every unpinned entry — BEFORE the doomed
        cold stage starts, not once pinned bytes already exceed
        budget.

        ``estimated_seconds`` (r22): the cost model's predicted fold
        seconds for this query — ADVISORY ONLY. It accumulates into the
        predicted-inflight-seconds signal (``snapshot``) the controller
        reads; it never rejects (bytes remain the only budget axis, so
        disabling the model restores pre-r22 admission exactly)."""
        t0 = time.monotonic()
        if not self._cv.acquire(blocking=False):
            w0 = time.perf_counter()
            self._cv.acquire()
            _LOCK_WAIT.observe(time.perf_counter() - w0)
        try:
            if faults.ACTIVE and faults.fires("serving.admission_reject"):
                self._reject(tenant, "fault_injected", t0)
            self._budget_check(tenant, t0, estimated_bytes)
            # Prune timed-out waiters off the heap top so a queue of
            # abandoned entries cannot block the immediate-admit path.
            while self._heap and self._heap[0].abandoned:
                heapq.heappop(self._heap)
            if self._active < self._limit() and not self._heap:
                self._active += 1
                self._vclock = max(
                    self._vclock,
                    self._tenant_vtime.get(tenant, 0.0),
                ) + 1.0 / self._weight(tenant)
                self._tenant_vtime[tenant] = self._vclock
                self._publish()
                _ADMITTED.inc(tenant=tenant)
                _WAIT_SECONDS.observe(0.0, tenant=tenant)
                self._inflight_seconds += max(float(estimated_seconds), 0.0)
                return _Ticket(self, tenant, 0.0, estimated_seconds)
            if self._waiting >= self._queue_cap():
                self._reject(tenant, "queue_full", t0)
            w = _Waiter(
                max(self._vclock, self._tenant_vtime.get(tenant, 0.0))
                + 1.0 / self._weight(tenant),
                next(self._seq),
                tenant,
            )
            self._tenant_vtime[tenant] = w.vtime
            heapq.heappush(self._heap, w)
            self._waiting += 1
            self._publish()
            deadline = t0 + self._timeout()
            while not w.granted:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    w.abandoned = True
                    self._waiting -= 1
                    self._publish()
                    self._reject(tenant, "timeout", t0)
                self._cv.wait(timeout=remaining)
            waited = time.monotonic() - t0
            _ADMITTED.inc(tenant=tenant)
            _WAIT_SECONDS.observe(waited, tenant=tenant)
            self._inflight_seconds += max(float(estimated_seconds), 0.0)
            return _Ticket(self, tenant, waited, estimated_seconds)
        finally:
            self._cv.release()

    def _budget_check(
        self, tenant: str, t0: float, estimated_bytes: int = 0
    ) -> None:
        """Reject when the HBM residency pool has no reclaimable
        headroom: pinned bytes (in-flight folds) already at/over budget
        means eviction cannot make room for this query's staging — and
        (r13) when the query's ESTIMATED staging bytes cannot fit the
        budget's unpinned headroom either, so a doomed cold stage is
        refused before it moves a single byte."""
        if self._budget_fn is None:
            return
        try:
            snap = self._budget_fn() or {}
        except Exception:
            return  # budget view is advisory; never fail admission on it
        budget = snap.get("budget_bytes") or 0
        pinned = snap.get("pinned_bytes") or 0
        if budget > 0 and pinned >= budget:
            self._reject(
                tenant,
                "hbm_budget",
                t0,
                detail=f"pinned {pinned}B >= budget {budget}B",
            )
        if budget > 0 and estimated_bytes > 0 and (
            pinned + estimated_bytes > budget
        ):
            self._reject(
                tenant,
                "hbm_budget",
                t0,
                detail=(
                    f"estimated staging {estimated_bytes}B > budget "
                    f"{budget}B - pinned {pinned}B"
                ),
            )

    def _reject(self, tenant: str, reason: str, t0: float, detail=""):
        waited = time.monotonic() - t0
        _REJECTED.inc(reason=reason, tenant=tenant)
        _WAIT_SECONDS.observe(waited, tenant=tenant)
        raise AdmissionRejected(
            tenant,
            reason,
            queue_depth=self._waiting,
            waited_s=waited,
            detail=detail,
        )

    def queue_depth(self) -> int:
        """Live queue depth, lock-free (an int read is atomic in
        CPython; this is the advisory gate for the shared-scan window
        skip and the r16 controller — momentary staleness only costs a
        window that slept or skipped one arrival too early)."""
        return self._waiting

    def _release(self, estimated_seconds: float = 0.0) -> None:
        with self._cv:
            self._active -= 1
            self._inflight_seconds = max(
                self._inflight_seconds - max(float(estimated_seconds), 0.0),
                0.0,
            )
            while self._heap and self._active < self._limit():
                w = heapq.heappop(self._heap)
                if w.abandoned:
                    continue
                w.granted = True
                self._waiting -= 1
                self._active += 1
                self._vclock = max(self._vclock, w.vtime)
            self._publish()
            self._cv.notify_all()

    def _publish(self) -> None:
        _QUEUE_DEPTH.set(self._waiting)
        _ACTIVE.set(self._active)

    def snapshot(self) -> dict:
        """Admission state for /statusz (the r10 health plane) and the
        soak harness — including queue-wait and lock-wait quantiles,
        the r13 contention signals at ~1k-client depth."""
        with self._cv:
            return {
                "active": self._active,
                "queue_depth": self._waiting,
                "max_concurrent": self._limit(),
                "max_queue": self._queue_cap(),
                "vclock": round(self._vclock, 6),
                "tenants": {
                    t: round(v, 6)
                    for t, v in sorted(self._tenant_vtime.items())
                },
                "wait_p50_ms": round(
                    _WAIT_SECONDS.agg_quantile(0.5) * 1e3, 3
                ),
                "wait_p99_ms": round(
                    _WAIT_SECONDS.agg_quantile(0.99) * 1e3, 3
                ),
                "lock_wait_p99_ms": round(
                    _LOCK_WAIT.quantile(0.99) * 1e3, 3
                ),
                # r22: predicted fold-seconds backlog across admitted
                # queries (0 when the cost model is cold or off).
                "predicted_inflight_s": round(self._inflight_seconds, 6),
            }


# -- metadata staging-cost estimation (r13 satellite) ------------------------


def estimate_staging_bytes(table, columns=None) -> int:
    """A query's predicted HBM staging footprint from table METADATA:
    row count × encoded column widths, no data read.

    Width per column prefers the table's OBSERVED staged bytes-per-row
    (parallel/staging.OBSERVED_BPR, recorded at every staging insert —
    it reflects narrowing, f32 sketch staging, and int-dict codes);
    before any staging exists it falls back to the relation's raw host
    widths plus the 1-byte validity mask — deliberately conservative,
    since the check exists to refuse DOOMED cold stages."""
    from pixie_tpu.parallel.staging import OBSERVED_BPR
    from pixie_tpu.types import DataType

    stats = table.stats()
    rows = max(int(stats.num_rows), 0)
    if rows == 0:
        return 0
    bpr = OBSERVED_BPR.get(table.name)
    if bpr is None:
        widths = {
            DataType.BOOLEAN: 1,
            DataType.INT64: 8,
            DataType.FLOAT64: 8,
            DataType.STRING: 4,  # dictionary codes
            DataType.TIME64NS: 8,
            DataType.UINT128: 16,
        }
        names = set(columns) if columns else None
        bpr = 1.0  # validity mask
        for c in table.relation:
            if names is not None and c.name not in names:
                continue
            bpr += widths.get(c.data_type, 8)
    return int(rows * bpr)


def estimate_fold_seconds(table) -> float:
    """r22: the cost model's predicted fold seconds for a query over
    ``table`` (row count / pooled fold-lane throughput). 0.0 when the
    model is cold, shadowing, or off — the advisory simply disappears,
    exactly the pre-r22 admission surface."""
    from pixie_tpu.serving import cost_model

    if not cost_model.ACTIVE or cost_model.SHADOW:
        return 0.0
    try:
        rows = max(int(table.stats().num_rows), 0)
        pred = cost_model.estimate_fold_seconds(rows)
        return float(pred) if pred else 0.0
    except Exception:
        return 0.0


def make_store_estimator(table_store):
    """table_name -> estimated staging bytes, over a TableStore — the
    callable QueryBroker(staging_estimator=...) wants. Unknown tables
    estimate 0 (never reject what we cannot see)."""

    def estimate(table_name: str) -> int:
        table = table_store.get_table(table_name)
        if table is None:
            return 0
        try:
            return estimate_staging_bytes(table)
        except Exception:
            return 0

    return estimate
