"""HBM table-residency manager: the staged-table cache as a managed pool.

Ref posture: the reference's table store evicts cold Arrow batches under
a per-table byte limit (table.h:51 table_store_table_size_limit); our
device-side analogue is the MeshExecutor's staged-cache — HBM-resident
[D, nblk, B] blocks a table version is staged into once, served to every
matching query. Until r12 that cache was an entry-count OrderedDict
(staged_cache_cap=4), blind to the one metric that matters on a device:
BYTES (staging.py: host→HBM transfer is the cold-path bottleneck, and
HBM itself is the scarcest resource a serving fleet shares).

This pool does the accounting the OrderedDict couldn't:

- **Per-entry byte accounting.** An entry's cost is the sum of its
  device block nbytes (columns + mask + gids), computed once at insert
  (``staged_nbytes``). Live totals ride the shared /metrics registry as
  ``device_staged_bytes`` / ``device_staged_pinned_bytes`` so /statusz
  shows HBM residency without touching the device.
- **Query-scoped pinning.** A fold in flight pins its entry
  (``with pool.pin(key): ...``); pinned entries are NEVER evicted — not
  by the byte watermark, not by version supersession, not by the OOM
  clear. (Refcounted jax arrays would keep the memory alive anyway;
  evicting a pinned entry would only make the accounting lie while
  freeing nothing.) A superseded-but-pinned entry leaves the key table
  immediately (lookups miss) but its bytes stay accounted as a zombie
  until the last unpin reaps it. Eviction passes that SKIP a pinned
  entry check the ``serving.evict_pinned_attempt`` fault site so chaos
  tests can prove the skip happens.
- **LRU eviction with high/low watermarks.** With ``hbm_budget_mb`` set,
  an insert that pushes the pool past the high watermark (95% of
  budget) evicts least-recently-used unpinned entries until under the
  low watermark (80%) — hysteresis, so a pool hovering at budget does
  not evict one entry per insert. The entry-count cap
  (``staged_cache_cap``) still applies as a secondary bound.
"""

from __future__ import annotations

import threading
from typing import Any, Optional

from pixie_tpu.utils import faults, flags, metrics_registry, trace

_M = metrics_registry()
_STAGED_BYTES = _M.gauge(
    "device_staged_bytes",
    "Bytes of HBM-resident staged table blocks in the residency pool "
    "(including superseded entries still pinned by in-flight folds).",
)
_PINNED_BYTES = _M.gauge(
    "device_staged_pinned_bytes",
    "Bytes of staged blocks pinned by in-flight folds (never evictable).",
)
_ENTRIES = _M.gauge(
    "device_staged_entries", "Entries in the staged-table residency pool."
)
_EVICTIONS = _M.counter(
    "device_staged_cache_evictions_total",
    "HBM staged-table cache evictions (LRU cap, byte watermark, version "
    "change, or device OOM).",
)
_PIN_SKIPS = _M.counter(
    "device_staged_evict_pinned_skips_total",
    "Eviction passes that skipped an entry because an in-flight fold "
    "had it pinned.",
)

HIGH_WATERMARK = 0.95
LOW_WATERMARK = 0.80


def staged_nbytes(staged: Any) -> int:
    """Device bytes of a StagedColumns entry: column blocks + validity
    mask + (optional) gid blocks. jax arrays report their on-device
    nbytes; anything without the attribute (test shims) counts 0."""
    total = 0
    for a in getattr(staged, "blocks", {}).values():
        total += int(getattr(a, "nbytes", 0))
    mask = getattr(staged, "mask", None)
    if mask is not None:
        total += int(getattr(mask, "nbytes", 0))
    gids = getattr(staged, "gids", None)
    if gids is not None:
        total += int(getattr(gids, "nbytes", 0))
    return total


class _Entry:
    __slots__ = ("staged", "nbytes", "table_name", "version", "pins", "dead")

    def __init__(self, staged, nbytes, table_name, version):
        self.staged = staged
        self.nbytes = nbytes
        self.table_name = table_name
        self.version = version
        self.pins = 0
        self.dead = False  # superseded while pinned: reap at last unpin


class ResidencyPool:
    """The MeshExecutor's staged-table cache, byte-accounted and pinnable.

    API mirrors what pipeline.py needs: ``get``/``insert``/``items``/
    ``touch``/``clear`` plus the ``pin`` context manager. All methods are
    thread-safe — agents execute fragments on per-query threads, so
    concurrent queries hit one pool."""

    def __init__(
        self,
        cap_entries: Optional[int] = None,
        budget_bytes: Optional[int] = None,
    ):
        import collections

        self._lock = threading.Lock()
        self._entries: "collections.OrderedDict[Any, _Entry]" = (
            collections.OrderedDict()
        )
        # Superseded-while-pinned entries: out of the key table (lookups
        # must miss), bytes still resident until the last unpin.
        self._zombies: list[_Entry] = []
        self._cap_entries = cap_entries
        self._budget_bytes = budget_bytes
        self._used = 0
        self._pinned = 0
        # Device-resident ring windows (r13, serving/resident.py):
        # byte-accounted like staged entries and treated as permanently
        # pinned — never LRU-evicted, never OOM-cleared; only the ring
        # itself releases them (its own depth bound / table expiry).
        self._resident: dict = {}
        # HBM usage sampling (r15): pool state lands in the hbm_usage
        # self-telemetry table at most every hbm_snapshot_interval_s
        # (mutation-driven) plus a forced sample per telemetry flush.
        self._last_usage_ns = 0
        try:
            from pixie_tpu.parallel import profiler

            profiler.register_pool(self)
        except Exception:  # pragma: no cover - recorder is advisory
            pass

    # -- configuration (read per call so flag flips apply live) --------------
    def _cap(self) -> int:
        return (
            self._cap_entries
            if self._cap_entries is not None
            else flags.staged_cache_cap
        )

    def budget_bytes(self) -> int:
        if self._budget_bytes is not None:
            return self._budget_bytes
        return int(flags.hbm_budget_mb) * (1 << 20)

    # -- lookup --------------------------------------------------------------
    def get(self, key) -> Optional[Any]:
        """The staged entry for ``key`` (LRU-touched), or None."""
        with self._lock:
            e = self._entries.get(key)
            if e is None:
                return None
            self._entries.move_to_end(key)
            return e.staged

    def touch(self, key) -> None:
        with self._lock:
            if key in self._entries:
                self._entries.move_to_end(key)

    def items(self) -> list:
        """(key, staged) snapshot in LRU order (superset-reuse scan)."""
        with self._lock:
            return [(k, e.staged) for k, e in self._entries.items()]

    def values(self) -> list:
        with self._lock:
            return [e.staged for e in self._entries.values()]

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def __contains__(self, key) -> bool:
        with self._lock:
            return key in self._entries

    # -- mutation ------------------------------------------------------------
    def insert(self, key, staged, table_name, version) -> None:
        """Register a staged entry: supersede stale versions of the same
        table, account bytes, then enforce the byte watermark and the
        entry cap (LRU, pinned entries skipped)."""
        nbytes = staged_nbytes(staged)
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._retire_locked(old, reason="replaced")
            # A new version of a table supersedes every older staging of
            # it — queries must not keep hitting pre-write data.
            for k in [
                k
                for k, e in self._entries.items()
                if e.table_name == table_name and e.version != version
            ]:
                self._retire_locked(
                    self._entries.pop(k), reason="version"
                )
            e = _Entry(staged, nbytes, table_name, version)
            self._entries[key] = e
            self._used += nbytes
            budget = self.budget_bytes()
            if budget > 0 and self._used > budget * HIGH_WATERMARK:
                self._evict_to_locked(
                    int(budget * LOW_WATERMARK), protect=key
                )
            cap = self._cap()
            while len(self._entries) > cap:
                victim = self._lru_unpinned_locked(protect=key)
                if victim is None:
                    break  # everything pinned: over cap beats corruption
                self._retire_locked(
                    self._entries.pop(victim), reason="lru"
                )
            self._publish_locked()

    def clear(self, reason: str = "oom") -> None:
        """Drop every entry (the device-OOM clear-and-retry path).
        Pinned entries' bytes stay accounted as zombies until their
        folds unpin — an in-flight fold's blocks are not freed by
        removing our reference to them."""
        with self._lock:
            for k in list(self._entries):
                self._retire_locked(self._entries.pop(k), reason=reason)
            self._publish_locked()

    # -- resident ring windows (r13) -----------------------------------------
    def register_resident(self, key, nbytes: int) -> None:
        """Account a device-resident ring window's bytes: they count as
        used AND pinned (unevictable by any pool policy — the ring owns
        their lifetime), so the byte watermark, /statusz, and admission's
        headroom math all see HBM the rings occupy."""
        with self._lock:
            old = self._resident.pop(key, None)
            if old is not None:
                self._used -= old
                self._pinned -= old
            self._resident[key] = int(nbytes)
            self._used += int(nbytes)
            self._pinned += int(nbytes)
            self._publish_locked()

    def release_resident(self, key) -> None:
        """Free a ring window's accounting (ring rolled past it, or the
        table expired its rows)."""
        with self._lock:
            nbytes = self._resident.pop(key, None)
            if nbytes is not None:
                self._used -= nbytes
                self._pinned -= nbytes
                _EVICTIONS.inc(reason="resident_roll")
                self._publish_locked()

    # -- pinning -------------------------------------------------------------
    class _Pin:
        def __init__(self, pool: "ResidencyPool", key):
            self._pool = pool
            self._key = key
            self._entry: Optional[_Entry] = None

        def __enter__(self):
            self._entry = self._pool._pin(self._key)
            return self

        def __exit__(self, *exc):
            if self._entry is not None:
                self._pool._unpin(self._entry)
                self._entry = None
            return False

    def pin(self, key) -> "ResidencyPool._Pin":
        """Context manager: while held, the entry (if present at enter)
        cannot be evicted — a version bump or OOM clear retires it from
        the key table but its bytes stay accounted until exit. Pinning
        a missing key is a no-op (non-cacheable stagings never enter
        the pool)."""
        return ResidencyPool._Pin(self, key)

    def _pin(self, key) -> Optional[_Entry]:
        with self._lock:
            e = self._entries.get(key)
            if e is not None:
                e.pins += 1
                self._pinned += e.nbytes
                self._publish_locked()
            return e

    def _unpin(self, e: _Entry) -> None:
        with self._lock:
            e.pins -= 1
            self._pinned -= e.nbytes
            if e.pins == 0 and e.dead:
                # Superseded/cleared while this fold ran: reap now.
                self._zombies.remove(e)
                self._used -= e.nbytes
                _EVICTIONS.inc(reason="deferred")
            self._publish_locked()

    # -- internals (call under self._lock) -----------------------------------
    def _lru_unpinned_locked(self, protect=None):
        for k, e in self._entries.items():
            if k == protect:
                continue
            if e.pins > 0:
                if faults.ACTIVE:
                    faults.fires("serving.evict_pinned_attempt")
                _PIN_SKIPS.inc()
                continue
            return k
        return None

    def _evict_to_locked(self, target_bytes: int, protect=None) -> None:
        while self._used > target_bytes:
            victim = self._lru_unpinned_locked(protect=protect)
            if victim is None:
                break  # only pinned entries left; nothing evictable
            self._retire_locked(self._entries.pop(victim), reason="bytes")

    def _retire_locked(self, e: _Entry, reason: str) -> None:
        """Remove an entry already popped from the key table: free its
        accounting immediately when unpinned, else zombie it until the
        last unpin."""
        if e.pins > 0:
            if faults.ACTIVE:
                faults.fires("serving.evict_pinned_attempt")
            _PIN_SKIPS.inc()
            e.dead = True
            self._zombies.append(e)
            return
        self._used -= e.nbytes
        _EVICTIONS.inc(reason=reason)

    def _publish_locked(self) -> None:
        _STAGED_BYTES.set(self._used)
        _PINNED_BYTES.set(self._pinned)
        _ENTRIES.set(len(self._entries))
        if trace.ATTR_ACTIVE:
            self._sample_usage_locked(force=False)

    # -- HBM usage sampling (r15) --------------------------------------------
    def sample_usage(self, force: bool = True) -> None:
        """Take one hbm_usage snapshot (the telemetry flush forces one so
        the table is fresh even on an idle pool)."""
        with self._lock:
            self._sample_usage_locked(force=force)

    def _sample_usage_locked(self, force: bool) -> None:
        import time

        from pixie_tpu.parallel import profiler

        if not profiler.ACTIVE:
            return
        now_ns = time.time_ns()
        interval_ns = int(float(flags.hbm_snapshot_interval_s) * 1e9)
        if not force and now_ns - self._last_usage_ns < interval_ns:
            return
        self._last_usage_ns = now_ns
        # Per-table staged bytes/pins (live entries), per-table ring
        # bytes (resident keys are ("resident", table, window)), plus
        # one pool-scope summary row whose used/pinned match the
        # accounting EXACTLY (zombies included — in-flight folds hold
        # real HBM).
        per_table: dict[str, dict] = {}
        for e in self._entries.values():
            t = per_table.setdefault(
                e.table_name,
                {"used": 0, "pinned": 0, "resident": 0, "entries": 0},
            )
            t["used"] += e.nbytes
            t["pinned"] += e.nbytes if e.pins > 0 else 0
            t["entries"] += 1
        for key, nbytes in self._resident.items():
            name = (
                str(key[1])
                if isinstance(key, tuple) and len(key) >= 2
                else str(key)
            )
            t = per_table.setdefault(
                name, {"used": 0, "pinned": 0, "resident": 0, "entries": 0}
            )
            t["used"] += nbytes
            t["pinned"] += nbytes
            t["resident"] += nbytes
        budget = self.budget_bytes()
        rows = [
            {
                "time_ns": now_ns,
                "scope": "pool",
                "name": "",
                "used_bytes": self._used,
                "pinned_bytes": self._pinned,
                "resident_bytes": sum(self._resident.values()),
                "budget_bytes": budget,
                "entries": len(self._entries),
            }
        ]
        for name, t in sorted(per_table.items()):
            rows.append(
                {
                    "time_ns": now_ns,
                    "scope": "table",
                    "name": name,
                    "used_bytes": t["used"],
                    "pinned_bytes": t["pinned"],
                    "resident_bytes": t["resident"],
                    "budget_bytes": budget,
                    "entries": t["entries"],
                }
            )
        profiler.record_hbm_rows(rows)

    # -- observability -------------------------------------------------------
    def snapshot(self) -> dict:
        """Residency state for /statusz and heartbeat health payloads."""
        with self._lock:
            budget = self.budget_bytes()
            return {
                "entries": len(self._entries),
                "used_bytes": self._used,
                "pinned_bytes": self._pinned,
                "zombie_entries": len(self._zombies),
                "resident_windows": len(self._resident),
                "resident_bytes": sum(self._resident.values()),
                "budget_bytes": budget,
                "headroom_bytes": (
                    max(budget - self._used, 0) if budget > 0 else None
                ),
                "tables": sorted(
                    {e.table_name for e in self._entries.values()}
                ),
            }

    def used_bytes(self) -> int:
        with self._lock:
            return self._used

    def pinned_bytes(self) -> int:
        with self._lock:
            return self._pinned
