"""Datastore-backed persistence of observed fold shapes (prewarm replay).

The r8 table-create prewarm guesses ONE canonical query shape per table
(groupby(first string column).agg(count, sum of every f64 column)) and
compiles its fold at create time. That guess misses every real workload
quirk: a dashboard that group-bys a different column, min/max lanes, a
capacity driven by real group cardinality, block dtypes narrowed by the
actual data range. This store closes the loop: when a device query's
shape is simple enough to replay (bare-column group key on the device
dictionary path, bare-column agg args, no predicates/aux), the
MeshExecutor records the fold-relevant facts — key column, agg lanes,
capacity, the staged blocks' EXACT dtypes/geometry, the narrowed column
set — keyed ``foldsig/<table>`` in a vizier datastore (in-memory,
file-log, or sqlite backend). After a restart, ``prewarm_table`` replays
every recorded shape through the same ``_unit_programs`` path a real
query takes, producing bit-identical fold signatures — so the first
query after restart finds its executable AOT-compiled (or the
persistent .jax_cache entry deserializing) instead of compiling inline.
"""

from __future__ import annotations

import json
import logging
import threading
from typing import Optional

_log = logging.getLogger("pixie_tpu.serving")

# Shapes kept per table: enough for a dashboard's query mix; LRU-ish
# (oldest recorded shape drops first) so a churning workload converges.
MAX_SHAPES_PER_TABLE = 8

_PREFIX = "foldsig/"


class FoldSignatureStore:
    """Record/replay of observed fold shapes over a vizier datastore.

    A shape is a JSON dict with keys:
      ``key_col``   group-by column (device dictionary-code key path)
      ``lanes``     [[uda_name, arg_col|None, arg_dtype_name|None], ...]
      ``capacity``  padded group capacity the pass plan chose
      ``blocks``    {col: numpy dtype str} — EXACT staged block dtypes
      ``narrow``    [cols] staged frame-of-reference narrowed
      ``geometry``  [d, nblk, b] — the staged block geometry observed
    Everything the fold signature derives from, nothing it doesn't."""

    def __init__(self, datastore):
        self._ds = datastore
        self._lock = threading.Lock()

    def record(self, table_name: str, shape: dict) -> bool:
        """Append a shape for ``table_name`` (dedup by content; capped at
        MAX_SHAPES_PER_TABLE, oldest first out). Returns True when the
        store changed. Never raises — persistence is advisory."""
        try:
            blob = json.dumps(shape, sort_keys=True)
            with self._lock:
                shapes = self._load(table_name)
                if blob in shapes:
                    return False
                shapes.append(blob)
                del shapes[:-MAX_SHAPES_PER_TABLE]
                self._ds.set(
                    _PREFIX + table_name,
                    json.dumps(shapes).encode(),
                )
            return True
        except Exception:
            _log.warning(
                "fold-signature record failed for %r (ignored)",
                table_name,
                exc_info=True,
            )
            return False

    def shapes(self, table_name: str) -> list[dict]:
        """Recorded shapes for a table, oldest first; [] on any error."""
        try:
            with self._lock:
                return [json.loads(b) for b in self._load(table_name)]
        except Exception:
            return []

    def tables(self) -> list[str]:
        try:
            return [
                k[len(_PREFIX):] for k in self._ds.keys(prefix=_PREFIX)
            ]
        except Exception:
            return []

    def _load(self, table_name: str) -> list[str]:
        raw = self._ds.get(_PREFIX + table_name)
        if not raw:
            return []
        out = json.loads(raw.decode())
        return out if isinstance(out, list) else []


def shape_from_staged(m, specs, key_plan, staged, capacity) -> Optional[dict]:
    """Distill a replayable shape from a successful device aggregation,
    or None when the query is outside the replayable profile (predicates,
    aux arguments, LUT/host-gid key paths, windowing — their fold
    signatures need inputs prewarm cannot reconstruct from a record)."""
    from pixie_tpu.plan.expressions import ColumnRef

    if m.predicates:
        return None
    if key_plan.host_gids is not None or not isinstance(
        key_plan.device_expr, ColumnRef
    ):
        return None
    if getattr(staged, "int_dicts", None):
        return None  # int-dict LUTs ride aux: not reconstructible
    lanes = []
    for _out, arg_e, uda in specs:
        if not uda.reads_args:
            lanes.append([uda.name, None, None])
            continue
        if not isinstance(arg_e, ColumnRef):
            return None
        lanes.append(
            [uda.name, arg_e.name, [t.name for t in uda.arg_types]]
        )
    mask_shape = tuple(staged.mask.shape)
    return {
        "key_col": key_plan.device_expr.name,
        "lanes": lanes,
        "capacity": int(capacity),
        "blocks": {
            name: str(a.dtype) for name, a in staged.blocks.items()
        },
        "narrow": sorted(staged.narrow_offsets),
        "geometry": [int(x) for x in mask_shape],
    }
