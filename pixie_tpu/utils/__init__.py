"""Shared runtime utilities: metrics registry + flag/config system +
fault-injection registry + distributed query tracing."""

from pixie_tpu.utils import faults
from pixie_tpu.utils import trace
from pixie_tpu.utils.config import define_flag, flags
from pixie_tpu.utils.metrics import (
    Counter,
    Gauge,
    Histogram,
    metrics_registry,
)

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "metrics_registry",
    "define_flag",
    "flags",
    "faults",
    "trace",
]
