"""Flag/config system.

Ref: the reference's C++ gflags-with-env-defaults pattern
(pem_main.cc:28-36, DECLARE_int32(table_store_table_size_limit)
table.h:51) and Go pflag+viper. Flags are declared where they are used
(``define_flag``), read env overrides ``PIXIE_TPU_<UPPER_NAME>`` at first
access, and can be set programmatically (tests, embedders) via
``flags.set(name, value)``.
"""

from __future__ import annotations

import os
import threading
from typing import Any, Callable, Optional


class _Flags:
    def __init__(self):
        self._lock = threading.Lock()
        self._defs: dict[str, tuple[Any, Callable, str]] = {}
        self._values: dict[str, Any] = {}

    def define(
        self,
        name: str,
        default: Any,
        parser: Optional[Callable] = None,
        help_: str = "",
    ) -> None:
        with self._lock:
            if name in self._defs:
                return  # first definition wins (idempotent imports)
            if parser is None:
                if isinstance(default, bool):
                    parser = lambda s: s in (True, "1", "true", "True")
                elif isinstance(default, int):
                    parser = int
                elif isinstance(default, float):
                    parser = float
                else:
                    parser = str
            self._defs[name] = (default, parser, help_)

    def get(self, name: str) -> Any:
        with self._lock:
            if name in self._values:
                return self._values[name]
            if name not in self._defs:
                raise KeyError(f"flag {name!r} is not defined")
            default, parser, _ = self._defs[name]
            env = os.environ.get(f"PIXIE_TPU_{name.upper()}")
            value = parser(env) if env is not None else default
            self._values[name] = value
            return value

    def set(self, name: str, value: Any) -> None:
        with self._lock:
            if name not in self._defs:
                raise KeyError(f"flag {name!r} is not defined")
            self._values[name] = value

    def reset(self, name: str) -> None:
        """Forget a cached/overridden value (re-reads env on next get)."""
        with self._lock:
            self._values.pop(name, None)

    def describe(self) -> dict[str, tuple[Any, str]]:
        with self._lock:
            return {
                name: (self._values.get(name, d[0]), d[2])
                for name, d in sorted(self._defs.items())
            }

    def __getattr__(self, name: str) -> Any:
        if name.startswith("_"):
            raise AttributeError(name)
        return self.get(name)

    def __setattr__(self, name: str, value: Any) -> None:
        # `flags.x = v` must be equivalent to set("x", v): a plain
        # instance attribute would SHADOW __getattr__ forever, silently
        # decoupling later set() calls from reads.
        if name.startswith("_"):
            object.__setattr__(self, name, value)
        else:
            self.set(name, value)


flags = _Flags()


def define_flag(
    name: str,
    default: Any,
    parser: Optional[Callable] = None,
    help_: str = "",
) -> None:
    flags.define(name, default, parser, help_)


# -- engine-wide knobs (declared centrally; component-local flags are
#    declared next to their use) -------------------------------------------
define_flag(
    "device_block_rows",
    1 << 17,
    help_="Rows per staged device block (parallel/staging.py).",
)
define_flag(
    "streaming_stage",
    True,
    help_="Stream cold-path staging as a double-buffered window pipeline "
    "(host pack ∥ HBM transfer ∥ device fold) instead of materializing "
    "the whole table in HBM before the first FLOP (MeshExecutor). The "
    "monolithic path remains the fallback (multi-pass group windows, "
    "streaming failures) and still serves warm cache hits.",
)
define_flag(
    "streaming_window_rows",
    1 << 23,
    help_="Rows per streamed staging window (clamped to the table size; "
    "a single-window stream reproduces the monolithic geometry exactly).",
)
define_flag(
    "signature_buckets",
    True,
    help_="Bucket staging geometry so compiled-program signatures are "
    "coarse: block counts round up to quarter-octave pow2-scaled buckets "
    "(<=25% padding, masked) and stream-window geometry derives from the "
    "pow2-padded row count — two tables or stream windows landing in the "
    "same bucket share ONE compiled executable, and the bucketed shapes "
    "are process-stable so the persistent .jax_cache hits across runs.",
)
define_flag(
    "aot_compile",
    True,
    help_="AOT-compile the streamed-staging fold program "
    "(jit.lower().compile()) on a background thread while host pack and "
    "HBM transfer stream, so the cold XLA compile overlaps staging "
    "instead of preceding it; failures fall back to the in-line jit path "
    "(MeshExecutor.stream_fallback_errors).",
)
define_flag(
    "program_decompose",
    True,
    help_="Run warm/monolithic queries through separately-jitted, "
    "separately-cached init/fold/merge/finalize program units instead of "
    "one fused program: a query differing only in finalize reuses the "
    "expensive fold executable, and each smaller unit compiles faster. "
    "Off = the fused single-dispatch program (r6 behavior).",
)
define_flag(
    "sorted_compact",
    True,
    help_="Enable the r8 sort–compact segment-reduction lane on TPU-class "
    "platforms: HLL register maxes, count-min bucket counts, and "
    "high-cardinality min/max group-bys above segment.SORTED_MIN_ROWS "
    "ride sort → first-occurrence → compact → O(num_segments) scatter "
    "instead of the ~7ns/row full-length scalar scatter "
    "(ops/segment.sorted_segment_reduce_compact). CPU always keeps the "
    "direct scatter; tests can force either lane via "
    "segment.set_sorted_strategy().",
)
define_flag(
    "prewarm_compile",
    False,
    help_="At table-create time, kick the background AOT machinery for "
    "the table's bucketed stream-window geometry: a canonical "
    "count+sum(float64 columns) group-by(first string column) fold is "
    "lower().compile()d on the AOT thread, so a matching first query "
    "skips its fold compile (cold-breakdown key prewarm_hit) and the "
    "persistent .jax_cache deserializes during table setup instead of "
    "on the query's critical path (MeshExecutor.prewarm_table).",
)
define_flag(
    "staged_cache_cap",
    4,
    help_="LRU capacity of HBM-resident staged tables (MeshExecutor).",
)
define_flag(
    "keyplan_cache_cap",
    4,
    help_="LRU capacity of host-densified group-key plans (MeshExecutor).",
)
define_flag(
    "broker_max_pending",
    256,
    help_="Bound on buffered result messages per query at the broker; "
    "producers block when full (flow control, ref: "
    "query_result_forwarder.go:502).",
)
define_flag(
    "broker_publish_timeout_s",
    10.0,
    help_="How long a producer blocks on a full result queue before the "
    "message is dropped and counted (bus_publish_dropped_total).",
)
define_flag(
    "device_group_state_budget_mb",
    512,
    help_="Memory budget for per-group UDA state on device; group-bys "
    "whose state would exceed it run in multiple gid-window passes "
    "(high-cardinality spill/recombine).",
)
define_flag(
    "device_scan_limit_cap",
    1 << 20,
    help_="Largest LimitOp n the device scan path accepts; bigger outputs "
    "are host-engine work (shipping the whole selection back forfeits "
    "the offload).",
)
define_flag(
    "device_join",
    True,
    help_="Device sort-merge join lane (r19): standalone INNER/LEFT/RIGHT/"
    "OUTER equijoins ride the r8 sort–compact machinery instead of the "
    "host JoinNode when the shape qualifies (parallel/pipeline.py "
    "match_join). Off = every join runs on the host engine.",
)
define_flag(
    "device_join_min_rows",
    1 << 18,
    help_="Combined build+probe row floor below which a join stays on the "
    "host engine — staging two sides for a small join costs more than "
    "the Python hash join (analogous to SORTED_MIN_ROWS; provisional, "
    "CPU-tuned, pending the TPU campaign).",
)
define_flag(
    "device_join_max_out",
    1 << 24,
    help_="Largest device-join output cardinality (matches + sentinel "
    "null rows) accepted on the merge lane; bigger joins are host work "
    "(the bounded-fanout gather pads to a power-of-two cap and i32 "
    "prefix math must stay exact).",
)
define_flag(
    "mesh_axes",
    "",
    help_="Mesh geometry for MeshExecutor when no mesh is passed "
    "explicitly, as comma-separated name:size pairs, outermost axis "
    "first (e.g. 'hosts:2,d:4'). A size of -1 (at most one axis) "
    "means 'all remaining devices'. Empty: a flat single-host mesh "
    "'d:<ndevices>'. Geometry is part of every compiled program "
    "signature, so a geometry change can never reuse a stale "
    "executable (pixie_tpu/distributed/mesh.py).",
)
define_flag(
    "mesh_distributed_join",
    True,
    help_="On a multi-axis mesh, run device equijoins as a distributed "
    "sort-merge: range-partition both sides by packed key across the "
    "hosts axis (balanced by per-key join work from the exact host "
    "bincounts), sort + merge locally per shard, concatenate — "
    "instead of the v1 replicated all_gather sort. Bit-identical to "
    "the host EquijoinNode. Off, or on a flat mesh: the v1 replicated "
    "path runs unchanged.",
)
define_flag(
    "mesh_fold_placement",
    True,
    help_="Adds the mesh_fold rung to the placement ladder: when a "
    "query's estimated staging span exceeds every live agent's "
    "advertised HBM headroom, admission stops forcing a single-agent "
    "pick and plans the fold across the full fleet (spanning "
    "placement) instead of thrashing one agent's residency ring.",
)
define_flag(
    "mesh_fold_checkpoint",
    True,
    help_="Window-level fold checkpointing on multi-axis meshes (r23): "
    "the stream fold pulls its carried per-device UDA state host-side "
    "at every window boundary, so a mid-stream geometry failure "
    "(host loss, hung collective) resumes from the last completed "
    "window on the degraded geometry instead of refolding from "
    "scratch. Merge order is preserved, so sketches and group order "
    "stay bit-identical. No effect on a flat (single-host) mesh.",
)
define_flag(
    "mesh_dispatch_timeout_s",
    0.0,
    help_="Collective watchdog deadline (seconds) around each sharded "
    "mesh fold dispatch: a dispatch that blocks past the deadline is "
    "treated as a hung collective and re-planned on the next "
    "degradation rung (pixie_tpu/distributed/mesh.py ladder). 0 = "
    "derive the deadline from the r22 CostModel prediction x "
    "mesh_watchdog_rail_factor when the model has an opinion (no "
    "opinion = no watchdog). Negative disables the watchdog outright.",
)
define_flag(
    "mesh_watchdog_rail_factor",
    32.0,
    help_="Multiplier on the r22 CostModel's predicted fold-dispatch "
    "seconds when deriving the collective-watchdog deadline (only when "
    "mesh_dispatch_timeout_s is 0). Generous by design: the watchdog "
    "exists to catch HUNG collectives, not slow ones — a false trip "
    "costs a full re-plan on the degraded rung.",
)
define_flag(
    "mesh_breaker_threshold",
    2,
    help_="Consecutive geometry failures (host loss / collective "
    "timeout) on one mesh signature before the per-geometry breaker "
    "opens and new folds skip straight to the next degradation rung. "
    "0 disables the per-geometry breaker (every fold starts at full "
    "geometry).",
)
define_flag(
    "mesh_breaker_cooldown_s",
    30.0,
    help_="Seconds an open mesh-geometry rung stays skipped before a "
    "half-open trial is allowed back on that geometry (success closes "
    "the breaker and restores the rung; failure re-opens it).",
)
define_flag(
    "view_tail_placement",
    True,
    help_="Route a view hit's unflushed-tail delta fold to the view's "
    "maintain agent (the r18 tracker pick recorded at registration) "
    "instead of folding on the broker — the agent already holds the "
    "table's resident ring and the view's carried state. Off: tail "
    "folds run wherever the probe runs (broker-local).",
)
define_flag(
    "agent_expiry_s",
    2.0,
    help_="Heartbeat silence before an agent is pruned from plans "
    "(ref: 1 minute, agent_topic_listener.go:41; scaled down).",
)
define_flag(
    "agent_heartbeat_interval_s",
    0.5,
    help_="Agent heartbeat period (ref: ~5s, scaled down).",
)

# -- robustness (r9): deadlines, partial results, backoff, breaker ----------
define_flag(
    "query_deadline_s",
    0.0,
    help_="Per-query hard deadline propagated broker→agent→exec graph so "
    "a stalled fragment aborts everywhere, not just at the client "
    "(QueryDeadlineExceeded). 0 disables; the broker uses "
    "min(timeout_s, query_deadline_s) when set.",
)
define_flag(
    "partial_results",
    True,
    help_="When an agent dies, errors, or misses the deadline mid-query, "
    "the broker returns the rows it has plus a structured per-agent "
    "``degraded`` annotation on the QueryResult instead of raising "
    "(ref: query_result_forwarder.go:395 forwards partial results with "
    "per-agent timeout/cancel annotations). Off = r8 raise behavior.",
)
define_flag(
    "agent_backoff_initial_s",
    0.05,
    help_="Initial delay for agent control-bus reconnect backoff "
    "(transport.py RemoteBus; doubles per attempt up to "
    "agent_backoff_max_s, with jitter).",
)
define_flag(
    "agent_backoff_max_s",
    2.0,
    help_="Ceiling for the agent reconnect exponential backoff.",
)
define_flag(
    "agent_backoff_jitter",
    0.25,
    help_="Fractional jitter applied to each reconnect delay (delay *= "
    "1 + jitter*U[0,1)) so a restarted broker is not thundering-herded.",
)
define_flag(
    "agent_reconnect_max_tries",
    64,
    help_="Reconnect attempts before a RemoteBus gives up and stays "
    "closed (0 = retry forever).",
)
define_flag(
    "device_breaker_threshold",
    3,
    help_="Consecutive device fold/compile failures for one program key "
    "before the circuit breaker trips that key to the host engine "
    "(parallel/pipeline.py). 0 disables the breaker.",
)
define_flag(
    "device_breaker_cooldown_s",
    30.0,
    help_="Seconds a tripped device program key stays on the host engine "
    "before a half-open trial is allowed back on the mesh.",
)

# -- serving (r12): HBM residency, shared scans, admission control ----------
define_flag(
    "serving_enabled",
    False,
    help_="Multi-query serving mode (pixie_tpu/serving/): "
    "QueryBroker.execute_script routes through admission control "
    "(concurrency limit + per-tenant weighted fair queueing + HBM "
    "byte-budget check), rejecting with a structured AdmissionRejected "
    "on overload instead of queueing unboundedly. Off = the r11 "
    "one-query-at-a-time relay behavior.",
)
define_flag(
    "hbm_budget_mb",
    0,
    help_="HBM byte budget for the staged-table residency pool "
    "(serving/residency.py). Inserting past the high watermark (95% of "
    "the budget) evicts LRU unpinned entries until under the low "
    "watermark (80%); pinned entries (in-flight folds) are never "
    "evicted. 0 = no byte budget (entry-count staged_cache_cap only).",
)
define_flag(
    "shared_scans",
    True,
    help_="Coalesce concurrent compatible queries over the same staged "
    "table into ONE device fold dispatch (serving/shared_scan.py): "
    "queries whose fold signatures match (r7 decomposed units — output "
    "names and finalize modes excluded) share the leader's merged "
    "states and fan out per-query finalizes. Results are bit-identical "
    "to serial execution; saved dispatches are counted "
    "(serving_shared_scan_saved_dispatches_total) and each query's "
    "trace records shared_scan_batch_size.",
)
define_flag(
    "shared_scan_window_ms",
    0.0,
    help_="Batching window before a shared-scan leader dispatches: the "
    "leader waits this long for compatible queries to join its batch. "
    "0 (default) coalesces only queries that overlap the dispatch "
    "itself — no added latency; soak/serving harnesses raise it to "
    "trade p50 for dispatch reduction.",
)
define_flag(
    "admission_max_concurrent",
    8,
    help_="Queries executing concurrently through the broker's admission "
    "controller (serving/admission.py) before new arrivals queue.",
)
define_flag(
    "admission_max_queue",
    64,
    help_="Queued queries the admission controller holds before "
    "rejecting new arrivals with AdmissionRejected(reason=queue_full).",
)
define_flag(
    "admission_timeout_s",
    10.0,
    help_="Longest a query waits in the admission queue before a "
    "structured AdmissionRejected(reason=timeout) — a rejected query "
    "returns an error, never hangs.",
)
define_flag(
    "admission_tenant_weights",
    "",
    help_="Per-tenant weighted-fair-queueing weights, "
    "'tenant:weight,tenant:weight'. Unlisted tenants get weight 1.0; a "
    "tenant's queued queries accrue virtual time at 1/weight, so a "
    "2x-weighted tenant drains twice as fast under contention and a "
    "starved tenant's first query always schedules ahead of a heavy "
    "tenant's backlog tail.",
)

# -- predicate-batched shared scans + closed-loop admission (r16) ------------
define_flag(
    "shared_scan_predicate_batching",
    True,
    help_="Widen shared-scan compatibility from identical-signature to "
    "predicate-COMPATIBLE (serving/shared_scan.py ladder rung 2): "
    "concurrent queries matching on everything except their predicates "
    "batch into ONE fold dispatch whose per-query predicate masks "
    "evaluate inside a single scan of the staged blocks (masked "
    "partial-agg state lanes stacked on a slot axis, per-query finalize "
    "fan-out — bit-identical to serial). The batched executable is "
    "keyed by a predicate-ERASED fold signature + pow2 batch-width "
    "bucket, so batch composition changes never recompile; the "
    "serving_shared_scan_batch_width histogram is the headline metric.",
)
define_flag(
    "shared_scan_max_batch",
    16,
    help_="Most predicate slots one batched shared-scan dispatch "
    "serves; arrivals past it start the next batch. Bounds the batched "
    "program's state memory (B x per-query state lanes) and compile "
    "variety (widths bucket to pow2 up to this).",
)
define_flag(
    "admission_controller",
    False,
    help_="Close the admission loop (serving/controller.py): an "
    "SLO-window-driven adapter riding the cron runner reads admission "
    "wait quantiles, queue depth, device-dispatch wall time, and HBM "
    "residency, and actuates admission_max_concurrent / "
    "shared_scan_window_ms / hbm_budget_mb within guard rails — a "
    "controller, not a knob. Off = the r12 static flag values.",
)
define_flag(
    "admission_controller_interval_s",
    2.0,
    help_="Seconds between admission-controller evaluation ticks (the "
    "cron ticker period; each tick is one control-law step over the "
    "window since the previous tick).",
)
define_flag(
    "admission_controller_min_concurrent",
    2,
    help_="Guard rail: the controller never moves "
    "admission_max_concurrent below this floor.",
)
define_flag(
    "admission_controller_max_concurrent",
    128,
    help_="Guard rail: the controller never moves "
    "admission_max_concurrent above this ceiling.",
)
define_flag(
    "admission_controller_max_window_ms",
    50.0,
    help_="Guard rail: the controller never raises "
    "shared_scan_window_ms above this ceiling (floor is 0 — the window "
    "is already demand-gated on queue depth).",
)
define_flag(
    "admission_controller_max_hbm_mb",
    0,
    help_="Guard rail: ceiling for controller-raised hbm_budget_mb. 0 "
    "disables HBM actuation entirely (the controller never invents a "
    "budget and never touches one it cannot bound).",
)
define_flag(
    "admission_controller_holddown_windows",
    3,
    help_="Post-brake hold-down (r17 satellite): after the controller "
    "HALVES admission_max_concurrent on HBM pressure, concurrency "
    "raises are suppressed for this many evaluation windows — the "
    "brake's effect must be observed before the MIMD law may climb "
    "again (damps the 8->128->floor->16 oscillation the 1k-client "
    "trail showed). Further braking is always allowed; 0 disables "
    "the hold-down.",
)
define_flag(
    "admission_controller_wait_target_ms",
    250.0,
    help_="Control target: windowed admission-wait p50 above this "
    "raises concurrency (when HBM headroom allows); a p50 under a "
    "tenth of it with an empty queue decays concurrency back toward "
    "the configured baseline.",
)

# -- staging codec + device-resident ingest (r13) ----------------------------
define_flag(
    "staging_codec",
    True,
    help_="Compress host→HBM staging transfers with per-column "
    "lightweight encoders (ops/codec.py): RLE for runs, delta+narrow "
    "for timestamps/monotone ids, passthrough when neither pays. The "
    "host packs ENCODED shards, the wire carries the compressed "
    "representation, and a jitted device program decodes ahead of the "
    "fold — decoded blocks are bit-identical to an uncompressed "
    "transfer, so fold programs, staged-cache entries, and shared "
    "scans are untouched. Cold breakdowns gain stage_encode/"
    "stage_decode/wire_bytes/codec_ratio.",
)
define_flag(
    "staging_codec_min_ratio",
    1.4,
    help_="Minimum compression ratio (decoded bytes / wire bytes) an "
    "encoder must achieve at plan time before a column ships encoded; "
    "below it the column ships passthrough (encode+decode cycles are "
    "cheap but not free).",
)
define_flag(
    "resident_ingest",
    False,
    help_="Device-resident incremental ingest (serving/resident.py): "
    "table appends accumulate into HBM-resident ring windows (the r6 "
    "windowed layout, raw dtypes, codec-compressed on the wire), so a "
    "query over a hot table finds full windows already in HBM and "
    "stages only the cold tail — stage_transfer ≈ 0 for the "
    "in-window span. Ring entries are pinned and byte-accounted in "
    "the residency pool like staged entries.",
)
define_flag(
    "resident_window_rows",
    1 << 21,
    help_="Rows per device-resident ring window. Queries over a ring "
    "table stream at this window size so plan windows align with ring "
    "windows exactly (a resident window substitutes for a "
    "pack+transfer, bit for bit).",
)
define_flag(
    "resident_max_windows",
    64,
    help_="Ring depth per table: oldest resident windows are released "
    "(and their pool bytes freed) past this bound — the device-side "
    "ring-buffer analogue of the table store's size_limit expiry.",
)

# -- durability (r14): crash-restart recovery --------------------------------
define_flag(
    "durable_transport",
    False,
    help_="Persist the RemoteBus delivery identity (agent_id + epoch) "
    "and spill the in-flight ack window to a checksummed WAL under "
    "wal_dir (vizier/durability.py TransportWAL), so a full agent "
    "process restart replays unacked frames above the server's applied "
    "watermark — exactly-once across crash, not just reconnect. "
    "Requires wal_dir; no-op without it.",
)
define_flag(
    "durable_resident",
    False,
    help_="Mirror each ResidentRing's full HBM windows and its partial "
    "host buffer to a per-table spill log under wal_dir "
    "(vizier/durability.py RingSpill): a restarted agent re-stages its "
    "rings into HBM from disk before accepting queries instead of "
    "losing every hot window (stage_resident_hits recover without "
    "replaying appends). Requires wal_dir and resident_ingest.",
)
define_flag(
    "wal_dir",
    "",
    help_="Directory for durable-restart state: the transport WAL "
    "(transport.wal), the agent's durable registration/query markers "
    "(agent-<id>.db, id-keyed so co-located agents never share "
    "state), and per-table resident-ring spill files "
    "(resident/<table>.wal). Empty disables all durability even when "
    "the durable_* flags are set.",
)
define_flag(
    "wal_fsync",
    "always",
    help_="WAL fsync policy: 'always' fsyncs every appended record "
    "(survives node power loss), 'never' flushes to the OS page cache "
    "only (survives process crash — OOM-kill, deploy, SIGKILL — but "
    "not a kernel panic). tools/microbench_fault_overhead.py reports "
    "the cost of each under durability_overhead.",
)
define_flag(
    "transport_wal_mem_frames",
    64,
    help_="In-flight window frames kept decoded in memory when the "
    "transport WAL is on; older unacked frames keep only their seq and "
    "byte count in RAM and are re-read from the WAL at replay time "
    "(the ARIES-style spill bound).",
)

# -- transparent fragment failover (r17) -------------------------------------
define_flag(
    "fragment_failover",
    False,
    help_="Transparent fragment failover (vizier/broker.py): when a "
    "fragment is lost mid-query (heartbeat death, execute error, "
    "restart refusal, forwarder drop) the broker re-launches it on a "
    "surviving capable agent instead of synthesizing eos — the query "
    "completes with FULL, bit-identical results and a ``recovered`` "
    "annotation instead of a ``degraded`` one. Retries are "
    "exactly-once: every attempt carries a per-fragment result epoch, "
    "the broker applies exactly one attempt's output, and bridge "
    "pushes commit atomically per attempt (exec/router.py). Off = the "
    "r9 partial-results behavior.",
)
define_flag(
    "fragment_max_retries",
    2,
    help_="Most failover re-launches one fragment slot gets before the "
    "broker gives up and degrades the query (the r9 partial-results "
    "fallback). Hedged duplicates do not count against this budget.",
)
define_flag(
    "hedged_requests",
    False,
    help_="Hedged fragment dispatch (vizier/broker.py; Dean & Barroso, "
    "'The Tail at Scale'): when a fragment is still pending past the "
    "hedge delay — the per-program-key fold-latency quantile from "
    "agent heartbeats (``hedge_quantile``), or ``hedge_delay_ms`` when "
    "set — the broker launches a duplicate attempt on another capable "
    "agent. First fragment_done wins; the loser is cancelled through "
    "the r9 abort path and its output is dropped by the same "
    "fragment-epoch dedup retries use. Requires fragment_failover.",
)
define_flag(
    "hedge_quantile",
    0.99,
    help_="Fold-latency quantile (from the r11 per-program-key "
    "heartbeat histograms) a pending fragment must exceed before a "
    "hedge launches. Only 0.5 and 0.99 are tracked; values >= 0.99 "
    "read p99, lower values p50.",
)
define_flag(
    "hedge_delay_ms",
    0.0,
    help_="Fixed hedge delay override in milliseconds. 0 derives the "
    "delay from the fold-latency view (no latency data for the "
    "fragment's program keys = no hedge).",
)
define_flag(
    "ring_replication_factor",
    1,
    help_="Resident-ring replication (serving/resident.py + "
    "vizier/agent.py): hot ring windows replicate to factor-1 follower "
    "agents over the existing codec'd wire (the encoded window payload "
    "is republished, follower decodes device-side), byte-accounted in "
    "the follower's ResidencyPool and advertised in heartbeat "
    "residency snapshots — so fragment failover lands on an agent "
    "whose HBM already holds the hot windows (wire ~ 0) instead of a "
    "cold re-stage. A lagging replica (bounded by the leader's "
    "advertised watermark) falls back to re-staging from the table "
    "store — bit-identical either way. 1 disables replication.",
)
define_flag(
    "residency_placement",
    False,
    help_="Admission-time placement plane (serving/placement.py + "
    "vizier/broker.py): before planning, score every live data-plane "
    "agent for the query's table span by heartbeat-advertised HBM "
    "residency (staged-cache tables + resident/replica rings), then "
    "the r11 fold-latency view, then WFQ-weighted load, and route the "
    "scan to the winner by narrowing the planner's agent->table view. "
    "Shares one scorer with r17 fragment failover. Decisions surface "
    "as broker_placement_decisions_total{outcome=} and the /statusz "
    "placement section. Off routes by the planner's static ownership "
    "view as before.",
)
define_flag(
    "ring_rebalance",
    False,
    help_="Adaptive replica-ring rebalancer (serving/placement.py): a "
    "broker loop drains per-table placement heat each interval and "
    "reassigns WHICH tables replicate to WHICH followers, skipping "
    "followers above ring_rebalance_high_pct of their heartbeat HBM "
    "budget. Assignments ride the ring_replica topic as "
    "ring_replica_assign messages; agents without an assignment keep "
    "the deterministic r17 leader-rank attachment. Every move lands on "
    "an actuation trail (statusz placement.rebalancer). Requires "
    "residency_placement for the heat signal.",
)
define_flag(
    "ring_rebalance_interval_s",
    1.0,
    help_="Seconds between rebalancer ticks. Each tick is a hold "
    "unless the placement-heat window since the last tick is non-empty.",
)
define_flag(
    "ring_rebalance_high_pct",
    0.9,
    help_="HBM rail for the rebalancer: followers whose heartbeat "
    "ResidencyPool reports used_bytes above this fraction of "
    "budget_bytes are skipped when assigning replica followers "
    "(budget 0 = unlimited = always eligible).",
)

# -- robustness (r10): acked delivery + cluster health plane -----------------
# (transport_ack_* / transport_window_block_s are declared next to their
# use in vizier/transport.py.)
define_flag(
    "health_plane",
    True,
    help_="Broker-side cluster health view (vizier/broker.py): agent "
    "heartbeats carry device-breaker state, staging depth, and fold "
    "latency; execute_script skips agents whose OPEN breaker matches the "
    "query's program shape at planning time (recorded in "
    "degraded.skipped with reason breaker_open) instead of discovering "
    "them sick mid-query. Half-open breakers plan normally.",
)

# -- materialized views (r20) ------------------------------------------------
define_flag(
    "materialized_views",
    False,
    help_="Incremental materialized-view plane (serving/views.py + "
    "vizier/broker.py): registered PxL aggregation scripts are "
    "maintained by folding only new-since-watermark rows into "
    "persisted partial-agg state (StateBatch codec, datastore-backed "
    "like SLO rules / the admission controller), and "
    "QueryBroker.execute_script answers view-matching queries (fold "
    "signature + normalized predicate digest) from the merged state "
    "BEFORE admission ever queues them — a view_hit rung above "
    "ring_hit on the placement ladder. Reads merge the carried state "
    "with a delta fold over the unflushed tail and finalize, "
    "bit-identical to folding from scratch; freshness is stamped on "
    "every served QueryResult. Off: the probe short-circuits to a "
    "single attribute check on the query path.",
)
define_flag(
    "view_refresh_interval_s",
    1.0,
    help_="Default maintenance cadence for registered views: each "
    "view's CronScript ticker folds the new-since-watermark rows into "
    "the carried StateBatch and persists state + watermark every this "
    "many seconds (per-view override at register()).",
)
define_flag(
    "view_max_staleness_s",
    30.0,
    help_="Stale-view rail: when a view's last successful maintenance "
    "is older than this (maintenance wedged, breaker open, agent "
    "restarted long ago), the probe reports a miss and the query "
    "falls through to normal admission + execution instead of paying "
    "an unbounded tail fold on the read path. 0 disables the rail.",
)
