"""Lightweight distributed query tracing (r11).

Ref posture: Dapper (Sigelman et al., 2010) — per-query trace trees of
spans with (trace_id, span_id, parent_id) propagated across process
boundaries — exported in the OpenTelemetry data model, and dogfooded the
way the reference lands `stirling_error`/`probe_status` into its own
TableStore: finished spans are buffered here and periodically drained
into the node's `query_spans` table (ingest/self_telemetry.py) so PxL
scripts can query the engine about itself.

Design contract (mirrors utils/faults.py):

- **Near-zero cost when disabled.** Call sites gate on the module-level
  ``ACTIVE`` bool::

      if trace.ACTIVE:
          with trace.span("compile"): ...

  or call ``span()``/``record()`` directly — every entry point re-checks
  ``ACTIVE`` and returns a no-op immediately. The microbench
  (tools/microbench_fault_overhead.py ``trace_overhead`` key) holds the
  disabled path to <1% of the warm agg path and the transport RTT.

- **The query_id IS the trace_id.** The broker roots each query's trace
  at its query_id, so spans, inline degradation events, and the final
  ``degraded`` annotation are joinable on one key.

- **Propagation is explicit across processes, ambient within a
  thread.** A thread-local context stack makes nested ``span()`` calls
  parent automatically; crossing a boundary (broker → agent message,
  transport frame) carries ``{"trace_id", "span_id"}`` explicitly and
  the far side re-enters the context with ``context(trace_id, span_id)``.

- **Finished spans are data.** ``Span.to_dict()`` is wire-encodable
  (str/int/dict only); agents ship their spans back on ``fragment_done``
  and the broker merges by span_id (in-process clusters share this
  module's buffer, so dedup-by-id keeps the merge exact).
"""

from __future__ import annotations

import collections
import dataclasses
import threading
import time
import uuid
from typing import Any, Optional

from pixie_tpu.utils.config import define_flag, flags
from pixie_tpu.utils.metrics import metrics_registry

define_flag(
    "query_tracing",
    True,
    help_="Distributed query tracing: every query gets a Dapper-style "
    "span tree covering broker, each participating agent, each exec "
    "node, and per-window device stage/fold phases, assembled in "
    "QueryResult.profile and landed in the node's own query_spans table "
    "(utils/trace.py). Off = spans are never created (<1% residual "
    "overhead, gated by tools/microbench_fault_overhead.py).",
)
define_flag(
    "trace_buffer_cap",
    8192,
    help_="Finished-span ring buffer capacity per process; the oldest "
    "spans are evicted when self-telemetry ingestion falls behind.",
)
define_flag(
    "trace_otel_export",
    False,
    help_="Export each query's finished spans as an OTLP resourceSpans "
    "payload through the engine's pluggable OTel exporter (the "
    "exec/otel_sink_node.py path) in addition to the query_spans table.",
)
define_flag(
    "resource_attribution",
    True,
    help_="Continuous resource attribution (r15): threads executing a "
    "query carry an ambient (query_id, tenant, phase) label, so host "
    "profiler stack samples, device dispatch records "
    "(parallel/profiler.py), and HBM usage snapshots attribute CPU, "
    "device time, and bytes to the query/tenant that caused them. "
    "Off = attribution contexts and recorders are never entered (<1% "
    "residual cost, gated by tools/microbench_fault_overhead.py "
    "``profiler_overhead``).",
)

_SPAN_SECONDS = metrics_registry().histogram(
    "span_duration_seconds",
    "Finished trace-span durations by span name.",
)

# Fast gate read by every call site (one attribute load + branch when
# tracing is off). Synced with the ``query_tracing`` flag at import and by
# set_enabled()/refresh().
ACTIVE = False
# Resource-attribution gate (r15, flag ``resource_attribution``):
# identical posture to ACTIVE — every attribution entry point re-checks
# it and becomes a no-op immediately when off.
ATTR_ACTIVE = False

_BUF_LOCK = threading.Lock()
_FINISHED: "collections.deque[Span]" = collections.deque(
    maxlen=flags.trace_buffer_cap
)
_tls = threading.local()


def set_enabled(on: bool) -> None:
    """Flip tracing at runtime (also updates the ``query_tracing`` flag
    so flag introspection stays truthful)."""
    global ACTIVE
    ACTIVE = bool(on)
    flags.set("query_tracing", bool(on))


def set_attribution_enabled(on: bool) -> None:
    """Flip resource attribution at runtime (also updates the
    ``resource_attribution`` flag, and the parallel/profiler.py
    recorders' gate syncs from the same flag on their next refresh)."""
    global ATTR_ACTIVE
    ATTR_ACTIVE = bool(on)
    flags.set("resource_attribution", bool(on))


def refresh() -> None:
    """Re-read the ``query_tracing``/``resource_attribution`` flags into
    the ACTIVE/ATTR_ACTIVE gates."""
    global ACTIVE, ATTR_ACTIVE
    ACTIVE = bool(flags.query_tracing)
    ATTR_ACTIVE = bool(flags.resource_attribution)


def new_id() -> str:
    return uuid.uuid4().hex[:16]


@dataclasses.dataclass
class Span:
    """One finished (or in-flight) operation in a trace tree."""

    trace_id: str
    span_id: str
    parent_id: str  # "" at the root
    name: str
    start_unix_ns: int
    duration_ns: int = 0
    status: str = "ok"
    instance: str = ""
    attrs: dict = dataclasses.field(default_factory=dict)
    _start_pc_ns: int = 0  # perf_counter origin (not serialized)
    _finished: bool = False

    def to_dict(self) -> dict:
        """Wire-encodable form (plain str/int values + a str->scalar
        attrs map) — rides bus messages and transport frames as-is."""
        return {
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "name": self.name,
            "start_unix_ns": self.start_unix_ns,
            "duration_ns": self.duration_ns,
            "status": self.status,
            "instance": self.instance,
            "attrs": dict(self.attrs),
        }

    @staticmethod
    def from_dict(d: dict) -> "Span":
        return Span(
            trace_id=str(d.get("trace_id", "")),
            span_id=str(d.get("span_id", "")),
            parent_id=str(d.get("parent_id", "")),
            name=str(d.get("name", "")),
            start_unix_ns=int(d.get("start_unix_ns", 0)),
            duration_ns=int(d.get("duration_ns", 0)),
            status=str(d.get("status", "ok")),
            instance=str(d.get("instance", "")),
            attrs=dict(d.get("attrs") or {}),
        )


# -- thread-local context ----------------------------------------------------
def current() -> Optional[tuple[str, str]]:
    """(trace_id, span_id) of the innermost active span on this thread,
    or None outside any trace."""
    stack = getattr(_tls, "stack", None)
    return stack[-1] if stack else None


def _push(ctx: tuple[str, str]) -> None:
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    stack.append(ctx)


def _pop() -> None:
    stack = getattr(_tls, "stack", None)
    if stack:
        stack.pop()


class context:
    """Adopt an externally-propagated span context on this thread (the
    agent re-enters the broker's root span; a worker thread re-enters
    its query's fragment span). No-op with a None/empty context."""

    def __init__(self, trace_id: Optional[str], span_id: str = ""):
        self._ctx = (trace_id, span_id) if trace_id else None

    def __enter__(self):
        if self._ctx is not None:
            _push(self._ctx)
        return self

    def __exit__(self, *exc):
        if self._ctx is not None:
            _pop()
        return False


def context_of(span: "Optional[Span]") -> context:
    if span is None:
        return context(None)
    return context(span.trace_id, span.span_id)


# -- resource attribution (r15) ----------------------------------------------
# Thread ident -> (query_id, tenant, phase) for every thread currently
# doing work on a query's behalf. Unlike the span context stack (which is
# thread-LOCAL, invisible to other threads), this registry is readable
# ACROSS threads: the host profiler samples ``sys._current_frames()``,
# which is keyed by thread ident, and labels each sampled stack with the
# attribution the owning thread declared. Plain-dict assignment/removal
# is GIL-atomic, so readers take consistent snapshots without a lock.
_THREAD_ATTR: dict[int, tuple[str, str, str]] = {}


class attribution:
    """Declare that work on this thread — until exit — runs on behalf of
    ``(query_id, tenant, phase)``. Nested scopes restore the outer
    attribution on exit (a broker thread executing a local telemetry
    query inside an SLO evaluation re-attributes just that inner span of
    work). No-op when ``resource_attribution`` is off or query_id is
    empty."""

    __slots__ = ("_ctx", "_ident", "_prev")

    def __init__(self, query_id: Optional[str], tenant: str = "default",
                 phase: str = ""):
        self._ctx = (
            (str(query_id), str(tenant or "default"), str(phase))
            if ATTR_ACTIVE and query_id
            else None
        )

    def __enter__(self):
        if self._ctx is not None:
            self._ident = threading.get_ident()
            self._prev = _THREAD_ATTR.get(self._ident)
            _THREAD_ATTR[self._ident] = self._ctx
        return self

    def __exit__(self, *exc):
        if self._ctx is not None:
            if self._prev is None:
                _THREAD_ATTR.pop(self._ident, None)
            else:
                _THREAD_ATTR[self._ident] = self._prev
        return False


def current_attribution() -> Optional[tuple[str, str, str]]:
    """(query_id, tenant, phase) this thread is working for, or None."""
    if not ATTR_ACTIVE:
        return None
    return _THREAD_ATTR.get(threading.get_ident())


def thread_attributions() -> dict[int, tuple[str, str, str]]:
    """Snapshot of every attributed thread: ident -> (query_id, tenant,
    phase). The host profiler joins this against sys._current_frames()."""
    if not ATTR_ACTIVE:
        return {}
    return dict(_THREAD_ATTR)


def attributed(fn, phase: Optional[str] = None):
    """Wrap ``fn`` for submission to a worker thread/pool so the worker
    runs under the SUBMITTING thread's span context and resource
    attribution — the explicit cross-thread propagation rule (r11) now
    covering attribution too: pack/encode/compile workers doing a
    query's work show up in stack samples labeled with that query.
    ``phase`` overrides the attribution phase for the worker ("pack",
    "compile"). Returns ``fn`` unchanged when there is nothing to
    propagate."""
    if not (ACTIVE or ATTR_ACTIVE):
        return fn
    tctx = current()
    attr = current_attribution()
    if tctx is None and attr is None:
        return fn

    def run(*args, **kwargs):
        if tctx is not None:
            _push(tctx)
        scope = None
        if attr is not None:
            scope = attribution(
                attr[0], attr[1], attr[2] if phase is None else phase
            )
            scope.__enter__()
        try:
            return fn(*args, **kwargs)
        finally:
            if scope is not None:
                scope.__exit__(None, None, None)
            if tctx is not None:
                _pop()

    return run


# -- span lifecycle ----------------------------------------------------------
def begin(
    name: str,
    trace_id: Optional[str] = None,
    parent_id: Optional[str] = None,
    instance: str = "",
    attrs: Optional[dict] = None,
) -> Optional[Span]:
    """Start a span WITHOUT making it ambient (explicit-parent style for
    long scopes where a with-block is awkward, e.g. the broker's root
    span). Returns None when tracing is off; pair with ``finish()``."""
    if not ACTIVE:
        return None
    cur = current()
    if trace_id is None:
        trace_id = cur[0] if cur else new_id()
    if parent_id is None:
        parent_id = cur[1] if cur else ""
    s = Span(
        trace_id=trace_id,
        span_id=new_id(),
        parent_id=parent_id,
        name=name,
        start_unix_ns=time.time_ns(),
        instance=instance,
        attrs=dict(attrs or {}),
    )
    s._start_pc_ns = time.perf_counter_ns()
    return s


def finish(
    span: Optional[Span],
    status: Optional[str] = None,
    attrs: Optional[dict] = None,
) -> None:
    """Stamp the duration and buffer a span started with ``begin()``.
    Idempotent; None-safe (the disabled path passes None through)."""
    if span is None or span._finished:
        return
    span._finished = True
    span.duration_ns = time.perf_counter_ns() - span._start_pc_ns
    if status is not None:
        span.status = status
    if attrs:
        span.attrs.update(attrs)
    _record(span)


class span:
    """``with trace.span("compile"): ...`` — an ambient child span: nested
    spans on this thread parent to it automatically. ``.set(k=v)`` adds
    attributes; an exception propagating out marks status=error."""

    def __init__(
        self,
        name: str,
        trace_id: Optional[str] = None,
        parent_id: Optional[str] = None,
        instance: str = "",
        attrs: Optional[dict] = None,
    ):
        self._name = name
        self._trace_id = trace_id
        self._parent_id = parent_id
        self._instance = instance
        self._attrs = attrs
        self.span: Optional[Span] = None

    def __enter__(self):
        self.span = begin(
            self._name,
            trace_id=self._trace_id,
            parent_id=self._parent_id,
            instance=self._instance,
            attrs=self._attrs,
        )
        if self.span is not None:
            _push((self.span.trace_id, self.span.span_id))
        return self

    def set(self, **attrs) -> None:
        if self.span is not None:
            self.span.attrs.update(attrs)

    def __exit__(self, exc_type, exc, tb):
        if self.span is not None:
            _pop()
            finish(self.span, status="error" if exc_type else None)
        return False


def record(
    name: str,
    duration_ns: int,
    trace_id: Optional[str] = None,
    parent_id: Optional[str] = None,
    start_unix_ns: Optional[int] = None,
    status: str = "ok",
    instance: str = "",
    attrs: Optional[dict] = None,
) -> Optional[Span]:
    """Buffer an already-measured span (exec-node stats, transport ack
    latencies, device phase timings). Inherits the ambient context for
    missing trace/parent ids; drops the span when tracing is off OR no
    trace context is resolvable (orphan phases outside any query)."""
    if not ACTIVE:
        return None
    cur = current()
    if trace_id is None:
        if cur is None:
            return None
        trace_id = cur[0]
    if parent_id is None:
        parent_id = cur[1] if cur else ""
    if start_unix_ns is None:
        start_unix_ns = time.time_ns() - int(duration_ns)
    s = Span(
        trace_id=trace_id,
        span_id=new_id(),
        parent_id=parent_id,
        name=name,
        start_unix_ns=start_unix_ns,
        duration_ns=int(duration_ns),
        status=status,
        instance=instance,
        attrs=dict(attrs or {}),
    )
    s._finished = True
    _record(s)
    return s


def phase(name: str, duration_s: float, **attrs) -> None:
    """Device/staging phase helper: a measured sub-span under the ambient
    context (parallel/pipeline.py folds its COLD_PROFILE keys through
    here, so per-window pack/transfer/compile/fold become spans)."""
    record(name, int(duration_s * 1e9), attrs=attrs or None)


def _record(s: Span) -> None:
    with _BUF_LOCK:
        _FINISHED.append(s)
    _SPAN_SECONDS.observe(s.duration_ns / 1e9, name=s.name)


# -- buffer access -----------------------------------------------------------
def drain() -> list[Span]:
    """Remove and return every buffered finished span (the self-telemetry
    connector's consumption path — single consumer per process)."""
    with _BUF_LOCK:
        out = list(_FINISHED)
        _FINISHED.clear()
    return out


def spans_for(trace_id: str) -> list[Span]:
    """Copies of the buffered spans belonging to one trace (the buffer
    keeps them for self-telemetry ingestion)."""
    with _BUF_LOCK:
        return [s for s in _FINISHED if s.trace_id == trace_id]


def buffered_count() -> int:
    with _BUF_LOCK:
        return len(_FINISHED)


def clear() -> None:
    """Drop all buffered spans (tests)."""
    with _BUF_LOCK:
        _FINISHED.clear()


# -- profile assembly --------------------------------------------------------
def build_tree(spans: "list[dict | Span]") -> list[dict]:
    """Assemble span dicts into a parent->children forest, children sorted
    by start time. Unknown parents (dropped/evicted spans) root their
    subtree so a degraded trace still renders."""
    nodes: dict[str, dict] = {}
    ordered = []
    for s in spans:
        d = dict(s.to_dict() if isinstance(s, Span) else s)
        d["children"] = []
        prev = nodes.get(d["span_id"])
        if prev is None:
            nodes[d["span_id"]] = d
            ordered.append(d)
    roots = []
    for d in ordered:
        parent = nodes.get(d["parent_id"]) if d["parent_id"] else None
        if parent is None or parent is d:
            roots.append(d)
        else:
            parent["children"].append(d)
    for d in ordered:
        d["children"].sort(key=lambda c: c["start_unix_ns"])
    roots.sort(key=lambda c: c["start_unix_ns"])
    return roots


def spans_to_otel(spans: "list[dict | Span]", service: str = "pixie_tpu"):
    """OTLP/JSON resourceSpans payload for a span list — same data model
    the exec/otel_sink_node.py sink emits, so any exporter accepting its
    payloads accepts these."""
    from pixie_tpu.exec.otel_sink_node import _attr_list

    out = []
    for s in spans:
        d = s.to_dict() if isinstance(s, Span) else s
        out.append(
            {
                "name": d["name"],
                "traceId": d["trace_id"],
                "spanId": d["span_id"],
                "parentSpanId": d["parent_id"],
                "startTimeUnixNano": str(int(d["start_unix_ns"])),
                "endTimeUnixNano": str(
                    int(d["start_unix_ns"]) + int(d["duration_ns"])
                ),
                "attributes": _attr_list(
                    list(dict(d.get("attrs") or {}).items())
                    + [("status", d.get("status", "ok")),
                       ("instance", d.get("instance", ""))]
                ),
            }
        )
    return {
        "resourceSpans": [
            {
                "resource": {
                    "attributes": _attr_list([("service.name", service)])
                },
                "scopeSpans": [{"spans": out}],
            }
        ]
    }


refresh()
