"""Lightweight distributed query tracing (r11).

Ref posture: Dapper (Sigelman et al., 2010) — per-query trace trees of
spans with (trace_id, span_id, parent_id) propagated across process
boundaries — exported in the OpenTelemetry data model, and dogfooded the
way the reference lands `stirling_error`/`probe_status` into its own
TableStore: finished spans are buffered here and periodically drained
into the node's `query_spans` table (ingest/self_telemetry.py) so PxL
scripts can query the engine about itself.

Design contract (mirrors utils/faults.py):

- **Near-zero cost when disabled.** Call sites gate on the module-level
  ``ACTIVE`` bool::

      if trace.ACTIVE:
          with trace.span("compile"): ...

  or call ``span()``/``record()`` directly — every entry point re-checks
  ``ACTIVE`` and returns a no-op immediately. The microbench
  (tools/microbench_fault_overhead.py ``trace_overhead`` key) holds the
  disabled path to <1% of the warm agg path and the transport RTT.

- **The query_id IS the trace_id.** The broker roots each query's trace
  at its query_id, so spans, inline degradation events, and the final
  ``degraded`` annotation are joinable on one key.

- **Propagation is explicit across processes, ambient within a
  thread.** A thread-local context stack makes nested ``span()`` calls
  parent automatically; crossing a boundary (broker → agent message,
  transport frame) carries ``{"trace_id", "span_id"}`` explicitly and
  the far side re-enters the context with ``context(trace_id, span_id)``.

- **Finished spans are data.** ``Span.to_dict()`` is wire-encodable
  (str/int/dict only); agents ship their spans back on ``fragment_done``
  and the broker merges by span_id (in-process clusters share this
  module's buffer, so dedup-by-id keeps the merge exact).
"""

from __future__ import annotations

import collections
import dataclasses
import threading
import time
import uuid
from typing import Any, Optional

from pixie_tpu.utils.config import define_flag, flags
from pixie_tpu.utils.metrics import metrics_registry

define_flag(
    "query_tracing",
    True,
    help_="Distributed query tracing: every query gets a Dapper-style "
    "span tree covering broker, each participating agent, each exec "
    "node, and per-window device stage/fold phases, assembled in "
    "QueryResult.profile and landed in the node's own query_spans table "
    "(utils/trace.py). Off = spans are never created (<1% residual "
    "overhead, gated by tools/microbench_fault_overhead.py).",
)
define_flag(
    "trace_buffer_cap",
    8192,
    help_="Finished-span ring buffer capacity per process; the oldest "
    "spans are evicted when self-telemetry ingestion falls behind.",
)
define_flag(
    "trace_otel_export",
    False,
    help_="Export each query's finished spans as an OTLP resourceSpans "
    "payload through the engine's pluggable OTel exporter (the "
    "exec/otel_sink_node.py path) in addition to the query_spans table.",
)

_SPAN_SECONDS = metrics_registry().histogram(
    "span_duration_seconds",
    "Finished trace-span durations by span name.",
)

# Fast gate read by every call site (one attribute load + branch when
# tracing is off). Synced with the ``query_tracing`` flag at import and by
# set_enabled()/refresh().
ACTIVE = False

_BUF_LOCK = threading.Lock()
_FINISHED: "collections.deque[Span]" = collections.deque(
    maxlen=flags.trace_buffer_cap
)
_tls = threading.local()


def set_enabled(on: bool) -> None:
    """Flip tracing at runtime (also updates the ``query_tracing`` flag
    so flag introspection stays truthful)."""
    global ACTIVE
    ACTIVE = bool(on)
    flags.set("query_tracing", bool(on))


def refresh() -> None:
    """Re-read the ``query_tracing`` flag into the ACTIVE gate."""
    global ACTIVE
    ACTIVE = bool(flags.query_tracing)


def new_id() -> str:
    return uuid.uuid4().hex[:16]


@dataclasses.dataclass
class Span:
    """One finished (or in-flight) operation in a trace tree."""

    trace_id: str
    span_id: str
    parent_id: str  # "" at the root
    name: str
    start_unix_ns: int
    duration_ns: int = 0
    status: str = "ok"
    instance: str = ""
    attrs: dict = dataclasses.field(default_factory=dict)
    _start_pc_ns: int = 0  # perf_counter origin (not serialized)
    _finished: bool = False

    def to_dict(self) -> dict:
        """Wire-encodable form (plain str/int values + a str->scalar
        attrs map) — rides bus messages and transport frames as-is."""
        return {
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "name": self.name,
            "start_unix_ns": self.start_unix_ns,
            "duration_ns": self.duration_ns,
            "status": self.status,
            "instance": self.instance,
            "attrs": dict(self.attrs),
        }

    @staticmethod
    def from_dict(d: dict) -> "Span":
        return Span(
            trace_id=str(d.get("trace_id", "")),
            span_id=str(d.get("span_id", "")),
            parent_id=str(d.get("parent_id", "")),
            name=str(d.get("name", "")),
            start_unix_ns=int(d.get("start_unix_ns", 0)),
            duration_ns=int(d.get("duration_ns", 0)),
            status=str(d.get("status", "ok")),
            instance=str(d.get("instance", "")),
            attrs=dict(d.get("attrs") or {}),
        )


# -- thread-local context ----------------------------------------------------
def current() -> Optional[tuple[str, str]]:
    """(trace_id, span_id) of the innermost active span on this thread,
    or None outside any trace."""
    stack = getattr(_tls, "stack", None)
    return stack[-1] if stack else None


def _push(ctx: tuple[str, str]) -> None:
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    stack.append(ctx)


def _pop() -> None:
    stack = getattr(_tls, "stack", None)
    if stack:
        stack.pop()


class context:
    """Adopt an externally-propagated span context on this thread (the
    agent re-enters the broker's root span; a worker thread re-enters
    its query's fragment span). No-op with a None/empty context."""

    def __init__(self, trace_id: Optional[str], span_id: str = ""):
        self._ctx = (trace_id, span_id) if trace_id else None

    def __enter__(self):
        if self._ctx is not None:
            _push(self._ctx)
        return self

    def __exit__(self, *exc):
        if self._ctx is not None:
            _pop()
        return False


def context_of(span: "Optional[Span]") -> context:
    if span is None:
        return context(None)
    return context(span.trace_id, span.span_id)


# -- span lifecycle ----------------------------------------------------------
def begin(
    name: str,
    trace_id: Optional[str] = None,
    parent_id: Optional[str] = None,
    instance: str = "",
    attrs: Optional[dict] = None,
) -> Optional[Span]:
    """Start a span WITHOUT making it ambient (explicit-parent style for
    long scopes where a with-block is awkward, e.g. the broker's root
    span). Returns None when tracing is off; pair with ``finish()``."""
    if not ACTIVE:
        return None
    cur = current()
    if trace_id is None:
        trace_id = cur[0] if cur else new_id()
    if parent_id is None:
        parent_id = cur[1] if cur else ""
    s = Span(
        trace_id=trace_id,
        span_id=new_id(),
        parent_id=parent_id,
        name=name,
        start_unix_ns=time.time_ns(),
        instance=instance,
        attrs=dict(attrs or {}),
    )
    s._start_pc_ns = time.perf_counter_ns()
    return s


def finish(
    span: Optional[Span],
    status: Optional[str] = None,
    attrs: Optional[dict] = None,
) -> None:
    """Stamp the duration and buffer a span started with ``begin()``.
    Idempotent; None-safe (the disabled path passes None through)."""
    if span is None or span._finished:
        return
    span._finished = True
    span.duration_ns = time.perf_counter_ns() - span._start_pc_ns
    if status is not None:
        span.status = status
    if attrs:
        span.attrs.update(attrs)
    _record(span)


class span:
    """``with trace.span("compile"): ...`` — an ambient child span: nested
    spans on this thread parent to it automatically. ``.set(k=v)`` adds
    attributes; an exception propagating out marks status=error."""

    def __init__(
        self,
        name: str,
        trace_id: Optional[str] = None,
        parent_id: Optional[str] = None,
        instance: str = "",
        attrs: Optional[dict] = None,
    ):
        self._name = name
        self._trace_id = trace_id
        self._parent_id = parent_id
        self._instance = instance
        self._attrs = attrs
        self.span: Optional[Span] = None

    def __enter__(self):
        self.span = begin(
            self._name,
            trace_id=self._trace_id,
            parent_id=self._parent_id,
            instance=self._instance,
            attrs=self._attrs,
        )
        if self.span is not None:
            _push((self.span.trace_id, self.span.span_id))
        return self

    def set(self, **attrs) -> None:
        if self.span is not None:
            self.span.attrs.update(attrs)

    def __exit__(self, exc_type, exc, tb):
        if self.span is not None:
            _pop()
            finish(self.span, status="error" if exc_type else None)
        return False


def record(
    name: str,
    duration_ns: int,
    trace_id: Optional[str] = None,
    parent_id: Optional[str] = None,
    start_unix_ns: Optional[int] = None,
    status: str = "ok",
    instance: str = "",
    attrs: Optional[dict] = None,
) -> Optional[Span]:
    """Buffer an already-measured span (exec-node stats, transport ack
    latencies, device phase timings). Inherits the ambient context for
    missing trace/parent ids; drops the span when tracing is off OR no
    trace context is resolvable (orphan phases outside any query)."""
    if not ACTIVE:
        return None
    cur = current()
    if trace_id is None:
        if cur is None:
            return None
        trace_id = cur[0]
    if parent_id is None:
        parent_id = cur[1] if cur else ""
    if start_unix_ns is None:
        start_unix_ns = time.time_ns() - int(duration_ns)
    s = Span(
        trace_id=trace_id,
        span_id=new_id(),
        parent_id=parent_id,
        name=name,
        start_unix_ns=start_unix_ns,
        duration_ns=int(duration_ns),
        status=status,
        instance=instance,
        attrs=dict(attrs or {}),
    )
    s._finished = True
    _record(s)
    return s


def phase(name: str, duration_s: float, **attrs) -> None:
    """Device/staging phase helper: a measured sub-span under the ambient
    context (parallel/pipeline.py folds its COLD_PROFILE keys through
    here, so per-window pack/transfer/compile/fold become spans)."""
    record(name, int(duration_s * 1e9), attrs=attrs or None)


def _record(s: Span) -> None:
    with _BUF_LOCK:
        _FINISHED.append(s)
    _SPAN_SECONDS.observe(s.duration_ns / 1e9, name=s.name)


# -- buffer access -----------------------------------------------------------
def drain() -> list[Span]:
    """Remove and return every buffered finished span (the self-telemetry
    connector's consumption path — single consumer per process)."""
    with _BUF_LOCK:
        out = list(_FINISHED)
        _FINISHED.clear()
    return out


def spans_for(trace_id: str) -> list[Span]:
    """Copies of the buffered spans belonging to one trace (the buffer
    keeps them for self-telemetry ingestion)."""
    with _BUF_LOCK:
        return [s for s in _FINISHED if s.trace_id == trace_id]


def buffered_count() -> int:
    with _BUF_LOCK:
        return len(_FINISHED)


def clear() -> None:
    """Drop all buffered spans (tests)."""
    with _BUF_LOCK:
        _FINISHED.clear()


# -- profile assembly --------------------------------------------------------
def build_tree(spans: "list[dict | Span]") -> list[dict]:
    """Assemble span dicts into a parent->children forest, children sorted
    by start time. Unknown parents (dropped/evicted spans) root their
    subtree so a degraded trace still renders."""
    nodes: dict[str, dict] = {}
    ordered = []
    for s in spans:
        d = dict(s.to_dict() if isinstance(s, Span) else s)
        d["children"] = []
        prev = nodes.get(d["span_id"])
        if prev is None:
            nodes[d["span_id"]] = d
            ordered.append(d)
    roots = []
    for d in ordered:
        parent = nodes.get(d["parent_id"]) if d["parent_id"] else None
        if parent is None or parent is d:
            roots.append(d)
        else:
            parent["children"].append(d)
    for d in ordered:
        d["children"].sort(key=lambda c: c["start_unix_ns"])
    roots.sort(key=lambda c: c["start_unix_ns"])
    return roots


def spans_to_otel(spans: "list[dict | Span]", service: str = "pixie_tpu"):
    """OTLP/JSON resourceSpans payload for a span list — same data model
    the exec/otel_sink_node.py sink emits, so any exporter accepting its
    payloads accepts these."""
    from pixie_tpu.exec.otel_sink_node import _attr_list

    out = []
    for s in spans:
        d = s.to_dict() if isinstance(s, Span) else s
        out.append(
            {
                "name": d["name"],
                "traceId": d["trace_id"],
                "spanId": d["span_id"],
                "parentSpanId": d["parent_id"],
                "startTimeUnixNano": str(int(d["start_unix_ns"])),
                "endTimeUnixNano": str(
                    int(d["start_unix_ns"]) + int(d["duration_ns"])
                ),
                "attributes": _attr_list(
                    list(dict(d.get("attrs") or {}).items())
                    + [("status", d.get("status", "ok")),
                       ("instance", d.get("instance", ""))]
                ),
            }
        )
    return {
        "resourceSpans": [
            {
                "resource": {
                    "attributes": _attr_list([("service.name", service)])
                },
                "scopeSpans": [{"spans": out}],
            }
        ]
    }


refresh()
