"""Deterministic fault-injection registry (r9 chaos framework).

Ref posture: the reference proves its recovery paths with fault-injecting
tests around the result forwarder and agent tracker (agent death mid-query
forwards *partial* results with per-agent annotations,
query_result_forwarder.go:395,502,571; heartbeat expiry,
agent_topic_listener.go:41). This module is the injection half of that
story: production code declares named *sites* at the exact points that can
fail in the field (transport send/recv, handshake, agent heartbeat/execute,
broker forwarding, datastore append, staging pack, device fold dispatch;
r10 acked-delivery sites: ``transport.ack_drop`` — the server's cumulative
ack frame is lost on the wire, ``transport.replay_dup`` — the reconnect
replay ignores the server's applied watermark and re-sends delivered
frames, ``transport.conn_kill_midflight`` — the server kills the
connection AFTER applying a frame but before acking it, the
previously-ambiguous retry case; scope it ``@control``/``@data`` to target
one plane; r12 serving sites: ``serving.admission_reject`` — the broker's
admission controller force-rejects the query (chaos tests prove a
rejected query returns a structured AdmissionRejected, never a hang),
``serving.evict_pinned_attempt`` — checked whenever an eviction pass in
the HBM residency pool SKIPS an entry because an in-flight fold has it
pinned (chaos tests prove the pin held); r14 durability sites:
``transport.crash_restart`` — the process dies (SIGKILL posture: sockets
cut, no drain) immediately AFTER a frame reaches the wire and the WAL,
``wal.torn_write`` — a WAL append crashes mid-write() leaving a torn
record for recovery to truncate, ``resident.spill_corrupt`` — a ring
spill window record reads back corrupt and recovery must skip it, never
serve it; r17 failover sites: ``agent.kill_holding_fragment`` — the
agent process dies WHILE holding a fragment (heartbeats stop, results
withheld; the broker must fail the fragment over to a survivor),
``resident.replica_lag`` — a ring-replication frame is dropped so the
follower falls behind the leader's watermark (failover queries must
re-stage from the table store, bit-identical), ``hedge.both_complete``
— the broker skips cancelling a hedge loser so BOTH attempts complete
and the fragment-epoch dedup must drop exactly one; r19 join site:
``device.join_dispatch`` — the device sort-merge join lane fails after
planning accepts the shape, before staging (chaos tests prove the r9
breaker trips and the query completes bit-identical on the host
JoinNode); r23 mesh-recovery sites: ``mesh.host_loss`` — a host of the
multi-axis mesh dies mid-sharded-fold (the dispatch raises a
MeshGeometryError and the executor re-plans onto the next degradation
rung, bit-identical by the r21 invariant), ``mesh.collective_timeout``
— a cross-host collective hangs past the watchdog deadline (same
recovery, detected by deadline instead of error),
``mesh.checkpoint_corrupt`` — a window-boundary fold checkpoint reads
back corrupt on resume and recovery must discard it and refold from
scratch, never resurrect bad carry state (r14 RingSpill posture)); r24
ingest sites: ``ingest.parse_error`` — a ConnTracker's parser throws
mid-transfer-tick (the quarantine breaker must isolate that connection
while every other tracker processes the same tick),
``ingest.push_stall`` — the table-store/WAL/resident-ring push path
fails (rows counted as ``rows_dropped_push``, the shedding ladder is
forced to level >= 2 next tick), ``ingest.event_flood`` — admission
control rejects a data event at the door (counted ``event_flood``, the
exact-accounting invariant must still balance), ``ingest.tracker_leak``
— a conn_close event is lost before the connector sees it (the tracker
must be reclaimed by inactivity disposal, never leak)), and
tests/operators arm them deterministically.

Design contract:

- **Zero cost when disabled.** Call sites are gated on the module-level
  ``ACTIVE`` bool::

      if faults.ACTIVE and faults.fires("transport.send"):
          raise OSError("fault injected")

  With nothing armed, the cost is one attribute load + branch; no dict
  lookup, no string formatting, no lock. ``tools/microbench_fault_overhead
  .py`` holds this to <1% of the warm agg path and the transport
  round-trip.

- **Deterministic.** Each site owns a ``random.Random`` seeded from
  ``(seed, site name)``; with ``p=1`` and ``count``/``after``, firing is a
  pure function of how many times the site was checked — chaos tests never
  flake on scheduling.

- **Site behavior lives at the call site.** The registry only answers
  "does this check fire?"; whether that means a dropped frame, a raised
  exception, or a skipped heartbeat is the caller's choice (``check()`` is
  the raise-``FaultInjectedError`` convenience).

Arming: programmatic (``arm``/``disarm``/``reset``) or the ``fault_inject``
flag / ``PIXIE_TPU_FAULT_INJECT`` env::

    fault_inject="transport.send:count=1,agent.heartbeat@pem2:p=0.5:seed=7"

Spec grammar: comma-separated ``site[:key=value]*`` with keys ``p``
(probability, default 1), ``count`` (max fires, default unlimited),
``after`` (skip the first N checks), ``seed`` (default 0). Site names may
carry an ``@scope`` suffix; call sites with a natural instance (an agent
id) check both the bare and the scoped name via ``fires_scoped``.
"""

from __future__ import annotations

import random
import threading

from pixie_tpu.utils.config import define_flag, flags
from pixie_tpu.utils.metrics import metrics_registry

define_flag(
    "fault_inject",
    "",
    help_="Deterministic fault-injection spec: comma-separated "
    "site[:p=..][:count=..][:after=..][:seed=..] entries "
    "(pixie_tpu/utils/faults.py). Empty disables all sites at zero cost.",
)

_FIRED = metrics_registry().counter(
    "fault_injected_total", "Fault-injection site fires, by site."
)


class FaultInjectedError(RuntimeError):
    """Raised by ``check()`` when an armed site fires."""

    def __init__(self, site: str):
        super().__init__(f"fault injected: {site}")
        self.site = site


# Fast gate read by every call site. True iff at least one site is armed.
ACTIVE = False

_lock = threading.Lock()
_sites: dict[str, "_Site"] = {}


class _Site:
    __slots__ = ("name", "p", "count", "after", "checks", "fired", "_rng")

    def __init__(self, name, p=1.0, count=None, seed=0, after=0):
        self.name = name
        self.p = float(p)
        self.count = count if count is None else int(count)
        self.after = int(after)
        self.checks = 0
        self.fired = 0
        self._rng = random.Random(f"{seed}:{name}")

    def _fires(self) -> bool:
        self.checks += 1
        if self.checks <= self.after:
            return False
        if self.count is not None and self.fired >= self.count:
            return False
        if self.p < 1.0 and self._rng.random() >= self.p:
            return False
        self.fired += 1
        return True


def arm(
    site: str,
    p: float = 1.0,
    count: "int | None" = None,
    seed: int = 0,
    after: int = 0,
) -> None:
    """Arm (or re-arm, resetting counters) a site."""
    global ACTIVE
    with _lock:
        _sites[site] = _Site(site, p=p, count=count, seed=seed, after=after)
        ACTIVE = True


def disarm(site: str) -> None:
    global ACTIVE
    with _lock:
        _sites.pop(site, None)
        ACTIVE = bool(_sites)


def reset() -> None:
    """Disarm every site (tests call this in teardown)."""
    global ACTIVE
    with _lock:
        _sites.clear()
        ACTIVE = False


def fires(site: str) -> bool:
    """True iff ``site`` is armed and this check fires. Counts the check
    either way for ARMED sites (microbench uses p=0 arming to census site
    traffic). The un-armed probe is a lock-free dict read (~30ns): a
    query running while an operator injects into a DIFFERENT site must
    not pay the registry lock on every check (<1% overhead gate; dict
    reads are atomic in CPython, and arming re-checks under the lock)."""
    if _sites.get(site) is None:
        return False
    with _lock:
        s = _sites.get(site)
        if s is None or not s._fires():
            return False
    _FIRED.inc(site=site)
    return True


def fires_scoped(site: str, scope: str) -> bool:
    """Check the bare site name and its ``site@scope`` variant — lets a
    test target one agent/connection out of many. Only call under the
    ``ACTIVE`` gate (builds a string)."""
    return fires(site) or fires(f"{site}@{scope}")


def check(site: str) -> None:
    """Raise ``FaultInjectedError`` if the armed site fires."""
    if fires(site):
        raise FaultInjectedError(site)


def stats() -> dict[str, tuple[int, int]]:
    """{site: (checks, fired)} for currently-armed sites."""
    with _lock:
        return {name: (s.checks, s.fired) for name, s in _sites.items()}


def configure(spec: str) -> None:
    """Parse and arm a ``fault_inject``-flag spec (see module docstring)."""
    for entry in spec.split(","):
        entry = entry.strip()
        if not entry:
            continue
        parts = entry.split(":")
        site, kwargs = parts[0], {}
        for kv in parts[1:]:
            k, _, v = kv.partition("=")
            k = k.strip()
            if k == "p":
                kwargs["p"] = float(v)
            elif k == "count":
                kwargs["count"] = int(v)
            elif k == "after":
                kwargs["after"] = int(v)
            elif k == "seed":
                kwargs["seed"] = int(v)
            else:
                raise ValueError(
                    f"fault_inject: unknown key {k!r} in {entry!r}"
                )
        arm(site, **kwargs)


# Flag/env arming at import (tests use arm()/reset() directly).
if flags.fault_inject:
    configure(flags.fault_inject)
