"""Self-observability metrics registry.

Ref: src/common/metrics/metrics.h (prometheus-cpp registry shared by engine
components; e.g. table_store/table/table_metrics.h gauges,
socket_tracer/metrics.{h,cc} counters). Same shape here: process-global
registry of named counters/gauges with optional label sets, rendered in
Prometheus text exposition format for scraping/debugging.
"""

from __future__ import annotations

import threading
from typing import Optional


class _Metric:
    def __init__(self, name: str, help_: str):
        self.name = name
        self.help = help_
        self._lock = threading.Lock()
        self._values: dict[tuple, float] = {}

    def _key(self, labels: Optional[dict]) -> tuple:
        return tuple(sorted((labels or {}).items()))

    def labels(self, **labels) -> "_Bound":
        return _Bound(self, self._key(labels))

    def value(self, **labels) -> float:
        with self._lock:
            return self._values.get(self._key(labels), 0.0)

    def total(self, **labels) -> float:
        """Sum across every label set MATCHING the given subset (r15):
        ``rejected.total(tenant="a")`` sums all reasons for one tenant,
        ``rejected.total()`` sums everything. ``value()`` stays an exact
        key lookup."""
        want = set((labels or {}).items())
        with self._lock:
            return float(
                sum(
                    v
                    for key, v in self._values.items()
                    if not isinstance(v, dict) and want <= set(key)
                )
            )

    def samples(self) -> list[tuple[tuple, float]]:
        with self._lock:
            return sorted(self._values.items())

    def by_label(self, label: str) -> dict[str, float]:
        """Break the metric down by ONE label key (r24): sums every
        sample carrying that label, keyed by its value —
        ``drops.by_label("reason")`` → ``{"evict": 3.0, ...}``. Samples
        without the label are omitted."""
        out: dict[str, float] = {}
        with self._lock:
            for key, v in self._values.items():
                if isinstance(v, dict):
                    continue
                for k, lv in key:
                    if k == label:
                        out[lv] = out.get(lv, 0.0) + v
                        break
        return out


class Counter(_Metric):
    kind = "counter"

    def inc(self, amount: float = 1.0, **labels) -> None:
        key = self._key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount


class Gauge(_Metric):
    kind = "gauge"

    def set(self, value: float, **labels) -> None:
        with self._lock:
            self._values[self._key(labels)] = float(value)

    def inc(self, amount: float = 1.0, **labels) -> None:
        key = self._key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def dec(self, amount: float = 1.0, **labels) -> None:
        self.inc(-amount, **labels)


# Fixed exponential buckets: 0.1ms .. ~26s upper bounds (x2 per step),
# +Inf implicit. Chosen for span/ack latencies: sub-ms transport acks land
# in the low buckets, multi-second cold folds in the high ones.
DEFAULT_BUCKETS = tuple(0.0001 * 2.0**i for i in range(18))


class Histogram(_Metric):
    """Prometheus-style cumulative histogram (ref: prometheus-cpp
    Histogram in src/common/metrics/). ``observe()`` is the only write;
    exposition emits ``<name>_bucket{le=...}`` (cumulative, +Inf last),
    ``<name>_sum`` and ``<name>_count`` per label set."""

    kind = "histogram"

    def __init__(self, name: str, help_: str, buckets=None):
        super().__init__(name, help_)
        self.buckets = tuple(
            sorted(float(b) for b in (buckets or DEFAULT_BUCKETS))
        )

    def observe(self, value: float, **labels) -> None:
        key = self._key(labels)
        v = float(value)
        with self._lock:
            st = self._values.get(key)
            if st is None:
                st = self._values[key] = {
                    "counts": [0] * (len(self.buckets) + 1),
                    "sum": 0.0,
                    "count": 0,
                }
            i = 0
            while i < len(self.buckets) and v > self.buckets[i]:
                i += 1
            st["counts"][i] += 1
            st["sum"] += v
            st["count"] += 1

    def value(self, **labels) -> float:
        """Observation count (the scalar a histogram most naturally is)."""
        with self._lock:
            st = self._values.get(self._key(labels))
            return float(st["count"]) if st else 0.0

    def sum(self, **labels) -> float:
        with self._lock:
            st = self._values.get(self._key(labels))
            return float(st["sum"]) if st else 0.0

    def quantile(self, q: float, **labels) -> float:
        """Bucket-interpolated quantile estimate (the health plane's live
        p50/p99 view); 0.0 with no observations."""
        with self._lock:
            st = self._values.get(self._key(labels))
            if not st or not st["count"]:
                return 0.0
            counts = list(st["counts"])
        return self.quantile_of_counts(q, counts)

    def merged_counts(self, **labels) -> list[int]:
        """Per-bucket counts summed across every label set matching the
        given subset (r15): a tenant-labeled histogram still yields the
        aggregate distribution (``merged_counts()``) or one tenant's
        (``merged_counts(tenant="a")``). The SLO evaluator also diffs
        two of these snapshots to get a WINDOWED distribution."""
        want = set((labels or {}).items())
        out = [0] * (len(self.buckets) + 1)
        with self._lock:
            for key, st in self._values.items():
                if not isinstance(st, dict) or not (want <= set(key)):
                    continue
                for i, c in enumerate(st["counts"]):
                    out[i] += c
        return out

    def agg_quantile(self, q: float, **labels) -> float:
        """Quantile over the label-merged distribution (the snapshot
        views that predate per-tenant labels keep reading the aggregate)."""
        return self.quantile_of_counts(q, self.merged_counts(**labels))

    def quantile_of_counts(self, q: float, counts: list[int]) -> float:
        """Interpolated quantile of an explicit per-bucket count vector
        (shared by the live views and the SLO window-delta evaluator)."""
        total = sum(counts)
        if not total:
            return 0.0
        target = q * total
        cum = 0
        for i, c in enumerate(counts):
            if not c:
                continue
            if cum + c >= target:
                lo = self.buckets[i - 1] if i > 0 else 0.0
                hi = (
                    self.buckets[i]
                    if i < len(self.buckets)
                    else self.buckets[-1] * 2.0
                )
                frac = (target - cum) / c
                return lo + (hi - lo) * min(max(frac, 0.0), 1.0)
            cum += c
        return self.buckets[-1] * 2.0


class _Bound:
    def __init__(self, metric: _Metric, key: tuple):
        self._metric = metric
        self._key = key

    def inc(self, amount: float = 1.0) -> None:
        with self._metric._lock:
            self._metric._values[self._key] = (
                self._metric._values.get(self._key, 0.0) + amount
            )

    def set(self, value: float) -> None:
        with self._metric._lock:
            self._metric._values[self._key] = float(value)


class MetricsRegistry:
    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: dict[str, _Metric] = {}

    def counter(self, name: str, help_: str = "") -> Counter:
        return self._get_or_create(name, help_, Counter)

    def gauge(self, name: str, help_: str = "") -> Gauge:
        return self._get_or_create(name, help_, Gauge)

    def histogram(self, name: str, help_: str = "", buckets=None) -> Histogram:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = Histogram(name, help_, buckets=buckets)
                self._metrics[name] = m
            elif not isinstance(m, Histogram):
                raise TypeError(
                    f"metric {name!r} already registered as {type(m).__name__}"
                )
            return m

    def _get_or_create(self, name: str, help_: str, cls):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, help_)
                self._metrics[name] = m
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as {type(m).__name__}"
                )
            return m

    def collect(self) -> dict[str, dict]:
        """{name: {labels-tuple: value}} snapshot (for tests/inspection)."""
        with self._lock:
            metrics = list(self._metrics.values())
        return {m.name: dict(m.samples()) for m in metrics}

    def render_text(self) -> str:
        """Prometheus text exposition format (the /metrics payload)."""
        out = []
        with self._lock:
            metrics = list(self._metrics.values())
        def esc(v) -> str:
            # Exposition-format label escaping: backslash, quote, newline.
            return (
                str(v)
                .replace("\\", "\\\\")
                .replace('"', '\\"')
                .replace("\n", "\\n")
            )

        for m in metrics:
            if m.help:
                out.append(f"# HELP {m.name} {m.help}")
            out.append(f"# TYPE {m.name} {m.kind}")
            for key, val in m.samples():
                lbl = ",".join(f'{k}="{esc(v)}"' for k, v in key)
                if isinstance(m, Histogram):
                    # Cumulative bucket series + _sum/_count, +Inf last.
                    cum = 0
                    for le, c in zip(
                        list(m.buckets) + ["+Inf"],
                        val["counts"],
                    ):
                        cum += c
                        le_s = le if le == "+Inf" else f"{le:g}"
                        blbl = ",".join(
                            filter(None, [lbl, f'le="{le_s}"'])
                        )
                        out.append(f"{m.name}_bucket{{{blbl}}} {cum:g}")
                    suffix = f"{{{lbl}}}" if lbl else ""
                    out.append(f"{m.name}_sum{suffix} {val['sum']:g}")
                    out.append(f"{m.name}_count{suffix} {val['count']:g}")
                elif lbl:
                    out.append(f"{m.name}{{{lbl}}} {val:g}")
                else:
                    out.append(f"{m.name} {val:g}")
        return "\n".join(out) + "\n"


_registry = MetricsRegistry()


def metrics_registry() -> MetricsRegistry:
    return _registry
