"""Self-observability metrics registry.

Ref: src/common/metrics/metrics.h (prometheus-cpp registry shared by engine
components; e.g. table_store/table/table_metrics.h gauges,
socket_tracer/metrics.{h,cc} counters). Same shape here: process-global
registry of named counters/gauges with optional label sets, rendered in
Prometheus text exposition format for scraping/debugging.
"""

from __future__ import annotations

import threading
from typing import Optional


class _Metric:
    def __init__(self, name: str, help_: str):
        self.name = name
        self.help = help_
        self._lock = threading.Lock()
        self._values: dict[tuple, float] = {}

    def _key(self, labels: Optional[dict]) -> tuple:
        return tuple(sorted((labels or {}).items()))

    def labels(self, **labels) -> "_Bound":
        return _Bound(self, self._key(labels))

    def value(self, **labels) -> float:
        with self._lock:
            return self._values.get(self._key(labels), 0.0)

    def samples(self) -> list[tuple[tuple, float]]:
        with self._lock:
            return sorted(self._values.items())


class Counter(_Metric):
    kind = "counter"

    def inc(self, amount: float = 1.0, **labels) -> None:
        key = self._key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount


class Gauge(_Metric):
    kind = "gauge"

    def set(self, value: float, **labels) -> None:
        with self._lock:
            self._values[self._key(labels)] = float(value)

    def inc(self, amount: float = 1.0, **labels) -> None:
        key = self._key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def dec(self, amount: float = 1.0, **labels) -> None:
        self.inc(-amount, **labels)


class _Bound:
    def __init__(self, metric: _Metric, key: tuple):
        self._metric = metric
        self._key = key

    def inc(self, amount: float = 1.0) -> None:
        with self._metric._lock:
            self._metric._values[self._key] = (
                self._metric._values.get(self._key, 0.0) + amount
            )

    def set(self, value: float) -> None:
        with self._metric._lock:
            self._metric._values[self._key] = float(value)


class MetricsRegistry:
    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: dict[str, _Metric] = {}

    def counter(self, name: str, help_: str = "") -> Counter:
        return self._get_or_create(name, help_, Counter)

    def gauge(self, name: str, help_: str = "") -> Gauge:
        return self._get_or_create(name, help_, Gauge)

    def _get_or_create(self, name: str, help_: str, cls):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, help_)
                self._metrics[name] = m
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as {type(m).__name__}"
                )
            return m

    def collect(self) -> dict[str, dict]:
        """{name: {labels-tuple: value}} snapshot (for tests/inspection)."""
        with self._lock:
            metrics = list(self._metrics.values())
        return {m.name: dict(m.samples()) for m in metrics}

    def render_text(self) -> str:
        """Prometheus text exposition format (the /metrics payload)."""
        out = []
        with self._lock:
            metrics = list(self._metrics.values())
        def esc(v) -> str:
            # Exposition-format label escaping: backslash, quote, newline.
            return (
                str(v)
                .replace("\\", "\\\\")
                .replace('"', '\\"')
                .replace("\n", "\\n")
            )

        for m in metrics:
            if m.help:
                out.append(f"# HELP {m.name} {m.help}")
            out.append(f"# TYPE {m.name} {m.kind}")
            for key, val in m.samples():
                if key:
                    lbl = ",".join(f'{k}="{esc(v)}"' for k, v in key)
                    out.append(f"{m.name}{{{lbl}}} {val:g}")
                else:
                    out.append(f"{m.name} {val:g}")
        return "\n".join(out) + "\n"


_registry = MetricsRegistry()


def metrics_registry() -> MetricsRegistry:
    return _registry
