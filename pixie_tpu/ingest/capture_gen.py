"""Synthetic mixed-protocol capture generation (r24).

One place that knows how to fabricate valid request/response byte
exchanges for every shipped parser (http, http2/gRPC, dns, mysql,
pgsql, redis), so the chaos soak (tools/soak_ingest.py), the fuzz
corpus tests, and the microbench all replay the SAME wire shapes the
protocol tests assert on — a capture built here parses to at least one
record per exchange on a healthy pipe.

The builders are deterministic functions of an integer ``i`` so replays
are reproducible without any RNG, and a corrupted replay (the fuzz
tests flip bits / truncate / interleave garbage) still exercises real
framing logic rather than random noise the parsers reject trivially.
"""

from __future__ import annotations

import struct

from pixie_tpu.protocols import http2 as http2_proto

# -- per-protocol wire builders ---------------------------------------------


def http_exchange(i: int, body: str = "") -> tuple[bytes, bytes]:
    body = body or f"payload-{i}"
    req = (
        f"GET /api/v{i % 7}/items/{i} HTTP/1.1\r\n"
        f"Host: svc{i % 13}.example.com\r\n\r\n"
    ).encode()
    resp = (
        f"HTTP/1.1 200 OK\r\nContent-Length: {len(body)}\r\n"
        f"Content-Type: text/plain\r\n\r\n{body}"
    ).encode()
    return req, resp


def _h2_frame(ftype: int, fflags: int, stream_id: int, payload: bytes) -> bytes:
    return (
        len(payload).to_bytes(3, "big")
        + bytes([ftype, fflags])
        + stream_id.to_bytes(4, "big")
        + payload
    )


def _h2_headers(pairs) -> bytes:
    # Literal-without-indexing with plain strings: a valid HPACK
    # encoding every decoder must accept.
    out = bytearray()
    for name, value in pairs:
        out.append(0x00)
        nb, vb = name.encode(), value.encode()
        out.append(len(nb))
        out += nb
        out.append(len(vb))
        out += vb
    return bytes(out)


def http2_exchange(i: int, body: str = "") -> tuple[bytes, bytes]:
    """A gRPC call on stream 1. The request side includes the client
    connection preface, so each exchange is a self-contained conn."""
    sid = 1
    data = (body or f"grpc-msg-{i}").encode()
    req = (
        http2_proto.PREFACE
        + _h2_frame(
            http2_proto.HEADERS,
            http2_proto.FLAG_END_HEADERS,
            sid,
            _h2_headers(
                [
                    (":method", "POST"),
                    (":path", f"/px.api.Svc{i % 5}/Call"),
                    (":scheme", "http"),
                    ("content-type", "application/grpc"),
                ]
            ),
        )
        + _h2_frame(
            http2_proto.DATA,
            http2_proto.FLAG_END_STREAM,
            sid,
            b"\x00" + len(data).to_bytes(4, "big") + data,
        )
    )
    resp = (
        _h2_frame(
            http2_proto.HEADERS,
            http2_proto.FLAG_END_HEADERS,
            sid,
            _h2_headers(
                [(":status", "200"), ("content-type", "application/grpc")]
            ),
        )
        + _h2_frame(
            http2_proto.DATA, 0, sid, b"\x00\x00\x00\x00\x02ok"
        )
        + _h2_frame(
            http2_proto.HEADERS,
            http2_proto.FLAG_END_HEADERS | http2_proto.FLAG_END_STREAM,
            sid,
            _h2_headers([("grpc-status", "0"), ("grpc-message", "")]),
        )
    )
    return req, resp


def dns_exchange(i: int, body: str = "") -> tuple[bytes, bytes]:
    txid = i & 0xFFFF
    name = body or f"svc{i % 97}.default.svc.cluster.local"
    q = struct.pack(">HHHHHH", txid, 0x0100, 1, 0, 0, 0)
    for label in name.split("."):
        q += bytes([len(label)]) + label.encode()
    q += b"\x00" + struct.pack(">HH", 1, 1)  # A IN
    r = struct.pack(">HHHHHH", txid, 0x8180, 1, 1, 0, 0)
    enc = (
        b"".join(
            bytes([len(l)]) + l.encode() for l in name.split(".")
        )
        + b"\x00"
    )
    r += enc + struct.pack(">HH", 1, 1)
    r += struct.pack(">H", 0xC00C)  # compressed pointer to the query name
    addr = bytes([10, (i >> 8) & 0xFF, i & 0xFF, 9])
    r += struct.pack(">HHIH", 1, 1, 60, len(addr)) + addr
    return q, r


def _mypkt(seq: int, payload: bytes) -> bytes:
    return len(payload).to_bytes(3, "little") + bytes([seq]) + payload


def mysql_exchange(i: int, body: str = "") -> tuple[bytes, bytes]:
    sql = body or f"SELECT * FROM t{i % 31} WHERE id = {i}"
    req = _mypkt(0, b"\x03" + sql.encode())  # COM_QUERY
    # A one-column, one-row resultset.
    resp = _mypkt(1, b"\x01")
    resp += _mypkt(2, b"\x03def" + b"col0")
    resp += _mypkt(3, b"\xfe\x00\x00\x02\x00")  # EOF after columns
    val = str(i).encode()
    resp += _mypkt(4, bytes([len(val)]) + val)
    resp += _mypkt(5, b"\xfe\x00\x00\x02\x00")  # EOF after rows
    return req, resp


def _pg(tag: bytes, payload: bytes) -> bytes:
    return tag + struct.pack(">I", len(payload) + 4) + payload


def pgsql_exchange(i: int, body: str = "") -> tuple[bytes, bytes]:
    sql = body or f"SELECT name FROM users WHERE id = {i};"
    req = _pg(b"Q", sql.encode() + b"\x00")
    val = f"user-{i}".encode()
    resp = (
        _pg(
            b"D",
            struct.pack(">H", 1) + struct.pack(">i", len(val)) + val,
        )
        + _pg(b"C", b"SELECT 1\x00")
        + _pg(b"Z", b"I")
    )
    return req, resp


def _bulk(*parts: str) -> bytes:
    out = f"*{len(parts)}\r\n".encode()
    for x in parts:
        out += f"${len(x)}\r\n{x}\r\n".encode()
    return out


def redis_exchange(i: int, body: str = "") -> tuple[bytes, bytes]:
    val = body or f"value-{i}"
    req = _bulk("SET", f"key:{i % 101}", val) + _bulk("GET", f"key:{i % 101}")
    resp = b"+OK\r\n" + f"${len(val)}\r\n{val}\r\n".encode()
    return req, resp


EXCHANGES = {
    "http": http_exchange,
    "http2": http2_exchange,
    "dns": dns_exchange,
    "mysql": mysql_exchange,
    "pgsql": pgsql_exchange,
    "redis": redis_exchange,
}
PROTOCOLS = tuple(EXCHANGES)


def build_conn_events(
    conn, protocol: str, n_exchanges: int = 1, start: int = 0, body: str = ""
) -> list[tuple]:
    """The full capture-tuple sequence for one connection: open, then
    ``n_exchanges`` pipelined request/response exchanges (send/recv
    positions advance per direction), then close. Feed through
    SocketTraceConnector.replay or event-by-event."""
    from pixie_tpu.protocols.base import TraceRole

    mk = EXCHANGES[protocol]
    events: list[tuple] = [
        (
            "open",
            conn,
            protocol,
            TraceRole.CLIENT,
            f"10.0.{(start >> 8) & 0xFF}.{start & 0xFF}",
            4000 + (start % 1000),
        )
    ]
    spos = rpos = 0
    ts = (start + 1) * 1000
    for k in range(n_exchanges):
        req, resp = mk(start + k, body)
        events.append(("data", conn, "send", spos, req, ts))
        events.append(("data", conn, "recv", rpos, resp, ts + 500))
        spos += len(req)
        rpos += len(resp)
        ts += 1000
    events.append(("close", conn))
    return events
