"""Socket-trace connector: byte streams → protocol tables.

Ref: socket_trace_connector.h:89 — the reference's flagship connector
attaches eBPF probes, reassembles per-connection byte streams through
ConnTrackers, parses protocol frames, stitches request/response pairs,
and appends rows to per-protocol tables. On TPU hosts the eBPF capture
layer is out of scope (BASELINE: collection stays CPU-side), so this
connector consumes *socket events* — (conn, direction, position, bytes,
timestamp) tuples — from replayed captures or synthetic workloads, and
runs the SAME userspace pipeline: ConnTracker → DataStreamBuffer →
parser → stitcher → http_events / dns_events rows.

r24 overload-proofing (flag ``ingest_robustness``, default on):

- **Bounded memory**: per-tracker byte budgets (oldest head bytes evict
  first), a global ingest byte budget that rejects events at admission,
  per-DataTable pending-row caps, and inactivity-based tracker disposal
  (a conn_open with no conn_close no longer leaks its tracker forever).
- **Shedding ladder** — pressure = max(buffer-bytes fraction, table-row
  fraction); a stalled push path forces level ≥ 2::

      level 1 (≥0.50)  truncate string bodies at ingest_shed_body_cap
      level 2 (≥0.75)  + sample new connections (deterministic crc32)
      level 3 (≥0.90)  + evict tracker buffers down to budget/4

- **Exact drop accounting** — three chained conservation laws, each
  checkable at any quiescent point via ``ingest_status()``:

      (A) events_fed  == Σ per-cause attributions + events pending
      (B) frames_parsed == frames_stitched + frames_drained + pending
      (C) records_stitched == rows_emitted + rows dropped at table cap

  plus the push stage: rows_emitted == rows_pushed + rows_dropped_push
  + rows pending in tables. Every event lands in exactly one bucket.
- **Parser quarantine**: a per-connection breaker (faults-registry
  style) — ``ingest_quarantine_threshold`` strikes open it (buffers
  drained to cause 'quarantine', incoming events dropped), a cooldown
  later it half-opens for one trial tick, success closes it. One
  poisoned connection never aborts the transfer tick for the others.
- **Deterministic fault sites** ``ingest.parse_error`` /
  ``ingest.push_stall`` / ``ingest.event_flood`` /
  ``ingest.tracker_leak`` (utils/faults.py) drive the chaos soak in
  tools/soak_ingest.py.
"""

from __future__ import annotations

import dataclasses
import threading
import time
import zlib
from collections import deque
from typing import Optional

from pixie_tpu.ingest.http_gen import HTTP_EVENTS_REL
from pixie_tpu.ingest.source_connector import DataTable, SourceConnector
from pixie_tpu.protocols import dns as dns_proto
from pixie_tpu.protocols import http as http_proto
from pixie_tpu.protocols import http2 as http2_proto
from pixie_tpu.protocols import mysql as mysql_proto
from pixie_tpu.protocols import pgsql as pgsql_proto
from pixie_tpu.protocols import redis as redis_proto
from pixie_tpu.protocols.base import ConnTracker, TraceRole
from pixie_tpu.types import DataType, Relation, SemanticType
from pixie_tpu.utils import faults, metrics_registry
from pixie_tpu.utils.config import define_flag, flags

define_flag(
    "ingest_global_budget_bytes",
    64 << 20,
    help_="Global ingest byte budget across every ConnTracker buffer. "
    "Events arriving while the estimate exceeds it are dropped at "
    "admission (ledger cause 'global_budget'). The estimate grows per "
    "event and is re-anchored exactly each transfer tick.",
)
define_flag(
    "ingest_table_pending_rows",
    200_000,
    help_="Per-DataTable cap on rows buffered between transfer and "
    "push. Appends beyond it are rejected and counted (ledger cause "
    "'table_cap') — conservation law C stays exact.",
)
define_flag(
    "ingest_tracker_idle_s",
    300.0,
    help_="Dispose a tracker after this much inactivity even without a "
    "conn_close (ref: ConnTracker inactivity disposal). Its buffered "
    "events drain to ledger cause 'idle_evict'.",
)
define_flag(
    "ingest_shed_body_cap",
    256,
    help_="Shedding ladder level >=1: string row values truncate to "
    "this many characters before landing in tables.",
)
define_flag(
    "ingest_quarantine_threshold",
    3,
    help_="Parser exceptions from one connection before its quarantine "
    "breaker opens (buffers drained, events dropped).",
)
define_flag(
    "ingest_quarantine_cooldown_s",
    5.0,
    help_="Seconds a quarantine breaker stays open before a half-open "
    "trial tick re-admits the connection.",
)

_M = metrics_registry()
_EVENTS = _M.counter(
    "ingest_events_total", "Socket events fed to the ingest plane."
)
_DROPS = _M.counter(
    "ingest_drops_total",
    "Ingest events/rows dropped, labeled by ladder/budget reason.",
)
_ROWS = _M.counter(
    "ingest_rows_total", "Rows emitted by the socket tracer, by table."
)
_TRACKERS_G = _M.gauge(
    "ingest_trackers", "Live ConnTrackers in the socket tracer."
)
_BUFFER_G = _M.gauge(
    "ingest_buffer_bytes", "Bytes buffered across all tracker streams."
)
_SHED_G = _M.gauge(
    "ingest_shed_level", "Current shedding-ladder level (0-3)."
)
_QUARANTINED_G = _M.gauge(
    "ingest_quarantined", "Connections with an open quarantine breaker."
)

I, S, T = DataType.INT64, DataType.STRING, DataType.TIME64NS

# ref: dns_table.h kDNSElements
DNS_EVENTS_REL = Relation.of(
    ("time_", T, SemanticType.ST_TIME_NS),
    ("upid", S, SemanticType.ST_UPID),
    ("remote_addr", S, SemanticType.ST_IP_ADDRESS),
    ("remote_port", I),
    ("trace_role", I),
    ("req_header", S),
    ("req_body", S),
    ("resp_header", S),
    ("resp_body", S),
    ("latency", I, SemanticType.ST_DURATION_NS),
)

# ref: mysql_table.h kMySQLElements
MYSQL_EVENTS_REL = Relation.of(
    ("time_", T, SemanticType.ST_TIME_NS),
    ("upid", S, SemanticType.ST_UPID),
    ("remote_addr", S, SemanticType.ST_IP_ADDRESS),
    ("remote_port", I),
    ("trace_role", I),
    ("req_cmd", I),
    ("req_body", S),
    ("resp_status", I),
    ("resp_body", S),
    ("latency", I, SemanticType.ST_DURATION_NS),
)

# ref: pgsql_table.h kPGSQLElements
PGSQL_EVENTS_REL = Relation.of(
    ("time_", T, SemanticType.ST_TIME_NS),
    ("upid", S, SemanticType.ST_UPID),
    ("remote_addr", S, SemanticType.ST_IP_ADDRESS),
    ("remote_port", I),
    ("trace_role", I),
    ("req_cmd", S),
    ("req", S),
    ("resp", S),
    ("latency", I, SemanticType.ST_DURATION_NS),
)

# ref: redis_table.h kRedisElements
REDIS_EVENTS_REL = Relation.of(
    ("time_", T, SemanticType.ST_TIME_NS),
    ("upid", S, SemanticType.ST_UPID),
    ("remote_addr", S, SemanticType.ST_IP_ADDRESS),
    ("remote_port", I),
    ("trace_role", I),
    ("req_cmd", S),
    ("req_args", S),
    ("resp", S),
    ("latency", I, SemanticType.ST_DURATION_NS),
)

_PARSERS = {
    "http": http_proto.HttpParser(),
    "http2": http2_proto.Http2Parser(),
    "dns": dns_proto.DnsParser(),
    "mysql": mysql_proto.MysqlParser(),
    "pgsql": pgsql_proto.PgsqlParser(),
    "redis": redis_proto.RedisParser(),
}
_ROW_FNS = {
    "http": http_proto.record_to_row,
    "http2": http_proto.record_to_row,  # gRPC lands in http_events
    "dns": dns_proto.record_to_row,
    "mysql": mysql_proto.record_to_row,
    "pgsql": pgsql_proto.record_to_row,
    "redis": redis_proto.record_to_row,
}
_TABLE_FOR = {
    "http": "http_events",
    "http2": "http_events",
    "dns": "dns_events",
    "mysql": "mysql_events",
    "pgsql": "pgsql_events",
    "redis": "redis_events",
}

# Buffer-level causes come from DataStreamBuffer attribution; the rest
# are counted at the connector's admission/processing boundary.
EVENT_CAUSES = (
    "parsed",
    "parsed_meta",
    "stale_dup",
    "gap_skip",
    "resync",
    "evict",
    "drain",
    "quarantine",
    "idle_evict",
    "unknown_conn",
    "bad_direction",
    "post_close",
    "conn_sampled",
    "global_budget",
    "event_flood",
)
# Causes that represent shed/dropped data (vs. normal consumption).
DROP_CAUSES = frozenset(EVENT_CAUSES) - {"parsed", "parsed_meta"}


class IngestLedger:
    """Connector-wide event/frame/row accounting (r24).

    Per-tracker ledgers delta-sync into ``causes`` at transfer ticks and
    retirement; admission-path drops count here directly. All mutation
    happens under ``lock`` so the conservation laws hold exactly even
    with a feeder thread racing the transfer thread.
    """

    def __init__(self):
        self.lock = threading.Lock()
        self.events_fed = 0
        self.causes: dict[str, int] = {}
        # Frame/row totals for retired trackers (live trackers' counters
        # are added on top when a status snapshot is taken).
        self.frames_parsed = 0
        self.frames_stitched = 0
        self.frames_drained = 0
        self.records_stitched = 0
        self.rows_emitted = 0
        self.rows_dropped_table_cap = 0
        self.rows_dropped_push = 0
        self.rows_pushed = 0
        self.bodies_truncated = 0
        self.conns_sampled_out = 0
        self.quarantine_opens = 0
        self.leaked_closes = 0

    def count(self, cause: str, n: int = 1) -> None:
        with self.lock:
            self.causes[cause] = self.causes.get(cause, 0) + n

    def merge_causes(self, deltas: dict) -> None:
        if not deltas:
            return
        with self.lock:
            for cause, n in deltas.items():
                self.causes[cause] = self.causes.get(cause, 0) + n


@dataclasses.dataclass(frozen=True)
class ConnId:
    """Ref: conn_id_t — (upid, fd, generation) identifies a connection."""

    upid: str
    fd: int
    tsid: int = 0


class _Quarantine:
    """Per-connection breaker state (closed → open → half-open)."""

    __slots__ = ("strikes", "open_until", "half_open")

    def __init__(self):
        self.strikes = 0
        self.open_until: Optional[float] = None
        self.half_open = False


class SocketTraceConnector(SourceConnector):
    """Drives ConnTrackers from fed socket events (ref:
    SocketTraceConnector::TransferDataImpl iterating conn trackers)."""

    name = "socket_tracer"

    def __init__(self):
        super().__init__()
        self._lock = threading.Lock()
        self._trackers: dict[ConnId, ConnTracker] = {}
        self._protocol: dict[ConnId, str] = {}
        # r24 state. _robust caches the master flag at construction so
        # the per-event fast path is one attribute load.
        self._robust = bool(flags.ingest_robustness)
        self.ledger = IngestLedger()
        self._quarantine: dict[ConnId, _Quarantine] = {}
        self._global_bytes = 0  # estimate; re-anchored each tick
        self._global_budget = int(flags.ingest_global_budget_bytes)
        self._shed_level = 0
        self._push_stalled = False
        self._ev_synced = 0
        self._cause_synced: dict[str, int] = {}
        # Bounded memory of recently retired conns so late events count
        # as post_close / conn_sampled instead of unknown_conn.
        self._recently_closed: set[ConnId] = set()
        self._recently_closed_q: deque[ConnId] = deque()
        self._sampled_out: set[ConnId] = set()
        self._sampled_out_q: deque[ConnId] = deque()
        self._RECENT_CAP = 4096

    def init_impl(self) -> None:
        self._global_budget = int(flags.ingest_global_budget_bytes)
        cap = (
            flags.ingest_table_pending_rows if self._robust else None
        )
        self.tables = [
            DataTable("http_events", HTTP_EVENTS_REL, max_pending_rows=cap),
            DataTable("dns_events", DNS_EVENTS_REL, max_pending_rows=cap),
            DataTable("mysql_events", MYSQL_EVENTS_REL, max_pending_rows=cap),
            DataTable("pgsql_events", PGSQL_EVENTS_REL, max_pending_rows=cap),
            DataTable("redis_events", REDIS_EVENTS_REL, max_pending_rows=cap),
        ]

    def _remember(self, conn: ConnId, which: str) -> None:
        """Record a retired/sampled conn in a bounded set (under _lock)."""
        s, q = (
            (self._recently_closed, self._recently_closed_q)
            if which == "closed"
            else (self._sampled_out, self._sampled_out_q)
        )
        if conn not in s:
            s.add(conn)
            q.append(conn)
            while len(q) > self._RECENT_CAP:
                s.discard(q.popleft())

    def _record_error(self, error: str, context: dict) -> None:
        rec = self.error_recorder
        if rec is not None:
            try:
                rec(self.name, 2, error, context)
            except Exception:
                pass  # self-monitoring must never take down ingest

    # -- event feed (the capture boundary) -----------------------------------
    def conn_open(
        self,
        conn: ConnId,
        protocol: str,
        role: TraceRole = TraceRole.CLIENT,
        remote_addr: str = "",
        remote_port: int = 0,
    ) -> None:
        if protocol not in _PARSERS:
            raise ValueError(f"unsupported protocol {protocol!r}")
        if not self._robust:
            with self._lock:
                self._trackers[conn] = ConnTracker(
                    _PARSERS[protocol],
                    upid=conn.upid,
                    remote_addr=remote_addr,
                    remote_port=remote_port,
                    role=role,
                )
                self._protocol[conn] = protocol
            return
        led = self.ledger
        if self._shed_level >= 2:
            # Ladder level 2: deterministic new-connection sampling —
            # the same conn id always gets the same verdict, so a replay
            # sheds identically.
            key = f"{conn.upid}:{conn.fd}:{conn.tsid}".encode()
            if zlib.crc32(key) & 1:
                with self._lock:
                    self._remember(conn, "sampled")
                with led.lock:
                    led.conns_sampled_out += 1
                return
        tracker = ConnTracker(
            _PARSERS[protocol],
            upid=conn.upid,
            remote_addr=remote_addr,
            remote_port=remote_port,
            role=role,
            byte_budget=flags.ingest_stream_buffer_bytes,
            track_drops=True,
        )
        tracker.last_activity_ns = time.monotonic_ns()
        with self._lock:
            self._trackers[conn] = tracker
            self._protocol[conn] = protocol
            self._sampled_out.discard(conn)
            self._recently_closed.discard(conn)

    def data_event(
        self,
        conn: ConnId,
        direction: str,  # "send" | "recv"
        pos: int,
        data: bytes,
        timestamp_ns: int,
    ) -> None:
        """One captured chunk (ref: socket_trace.c data events carry
        per-direction byte positions so userspace can reassemble)."""
        if not self._robust:
            if direction != "send" and direction != "recv":
                raise ValueError(
                    f"data_event direction must be 'send' or 'recv', "
                    f"got {direction!r}"
                )
            with self._lock:
                tracker = self._trackers.get(conn)
            if tracker is None:
                return  # conn never opened (capture raced) — drop
            with tracker.lock:
                if direction == "send":
                    tracker.add_send(pos, data, timestamp_ns)
                else:
                    tracker.add_recv(pos, data, timestamp_ns)
            return
        led = self.ledger
        with led.lock:
            led.events_fed += 1
        if direction != "send" and direction != "recv":
            led.count("bad_direction")
            return
        if faults.ACTIVE and faults.fires("ingest.event_flood"):
            # The flood site models admission control rejecting a burst:
            # the event is dropped at the door, exactly counted.
            led.count("event_flood")
            return
        with self._lock:
            tracker = self._trackers.get(conn)
            if tracker is None:
                if conn in self._sampled_out:
                    led.count("conn_sampled")
                elif conn in self._recently_closed:
                    led.count("post_close")
                else:
                    led.count("unknown_conn")
                return
            if tracker.quarantined:
                led.count("quarantine")
                return
            if self._global_bytes >= self._global_budget:
                led.count("global_budget")
                return
            self._global_bytes += len(data)
        with tracker.lock:
            if tracker.retired:
                # Lost the race with retirement — the tracker's ledger
                # was already final-synced, so count at the connector.
                led.count("post_close")
                return
            tracker.last_activity_ns = time.monotonic_ns()
            if direction == "send":
                tracker.add_send(pos, data, timestamp_ns)
            else:
                tracker.add_recv(pos, data, timestamp_ns)

    def conn_close(self, conn: ConnId) -> None:
        if self._robust and faults.ACTIVE and faults.fires(
            "ingest.tracker_leak"
        ):
            # The close event is "lost" — the tracker must now be
            # reclaimed by inactivity disposal, or it leaks forever
            # (the exact bug this release fixes).
            with self.ledger.lock:
                self.ledger.leaked_closes += 1
            return
        with self._lock:
            tracker = self._trackers.get(conn)
        if tracker is not None:
            tracker.closed = True

    def replay(self, events) -> None:
        """Feed a sequence of (kind, ...) capture tuples:
        ("open", conn, protocol, role, remote_addr, remote_port),
        ("data", conn, direction, pos, bytes, timestamp_ns),
        ("close", conn)."""
        for ev in events:
            kind = ev[0]
            if kind == "open":
                self.conn_open(*ev[1:])
            elif kind == "data":
                self.data_event(*ev[1:])
            elif kind == "close":
                self.conn_close(ev[1])
            else:
                raise ValueError(f"unknown capture event {kind!r}")

    # -- the sample step ------------------------------------------------------
    def transfer_data_impl(self, ctx) -> None:
        if not self._robust:
            self._transfer_legacy()
            return
        led = self.ledger
        now = time.monotonic()
        now_ns = time.monotonic_ns()
        idle_ns = int(flags.ingest_tracker_idle_s * 1e9)
        with self._lock:
            items = list(self._trackers.items())
        # Exact pressure readings drive the ladder for this tick.
        total_bytes = 0
        for _, tracker in items:
            with tracker.lock:
                total_bytes += tracker.byte_size()
        budget = max(1, flags.ingest_global_budget_bytes)
        row_cap = max(1, flags.ingest_table_pending_rows)
        rows_frac = max(
            (t.occupancy / row_cap for t in self.tables), default=0.0
        )
        pressure = max(total_bytes / budget, rows_frac)
        level = 0
        if pressure >= 0.9:
            level = 3
        elif pressure >= 0.75:
            level = 2
        elif pressure >= 0.5:
            level = 1
        if self._push_stalled:
            level = max(level, 2)
        self._shed_level = level
        body_cap = flags.ingest_shed_body_cap
        q_threshold = flags.ingest_quarantine_threshold
        q_cooldown = flags.ingest_quarantine_cooldown_s
        retire: list[tuple[ConnId, str]] = []
        for conn, tracker in items:
            # Inactivity disposal: an open-but-silent tracker (lost
            # close event) drains to 'idle_evict' and retires.
            if (
                not tracker.closed
                and now_ns - tracker.last_activity_ns > idle_ns
            ):
                retire.append((conn, "idle_evict"))
                continue
            q = self._quarantine.get(conn)
            if q is not None and q.open_until is not None:
                if now < q.open_until:
                    continue  # breaker open: skip this tracker entirely
                # Cooldown elapsed → half-open trial tick.
                q.open_until = None
                q.half_open = True
                tracker.quarantined = False
                _QUARANTINED_G.dec()
            try:
                with tracker.lock:
                    if faults.ACTIVE and faults.fires(
                        "ingest.parse_error"
                    ):
                        raise RuntimeError(
                            "injected ingest.parse_error"
                        )
                    records = tracker.process_to_records()
            except Exception as e:
                if q is None:
                    q = self._quarantine.setdefault(conn, _Quarantine())
                q.strikes += 1
                if q.half_open or q.strikes >= q_threshold:
                    # Open (or re-open) the breaker: drain what's
                    # buffered, refuse new events until the cooldown.
                    q.half_open = False
                    q.open_until = now + q_cooldown
                    tracker.quarantined = True
                    with tracker.lock:
                        tracker.drain_all("quarantine")
                    with led.lock:
                        led.quarantine_opens += 1
                    _QUARANTINED_G.inc()
                    self._record_error(
                        str(e),
                        {
                            "event": "quarantine_open",
                            "conn": f"{conn.upid}/{conn.fd}/{conn.tsid}",
                            "strikes": q.strikes,
                        },
                    )
                continue
            if q is not None and q.half_open:
                # Trial tick survived: breaker closes, slate wiped.
                del self._quarantine[conn]
            if records:
                self._emit_rows(conn, tracker, records, level, body_cap)
            with tracker.lock:
                done = tracker.closed and (
                    tracker.byte_size() == 0
                    and tracker.frames_pending() == 0
                )
            if done:
                retire.append((conn, "drain"))
            elif level >= 3:
                # Ladder level 3: shed the oldest buffered bytes down to
                # a quarter of the per-tracker budget.
                target = flags.ingest_stream_buffer_bytes // 4
                with tracker.lock:
                    for s in (tracker.send, tracker.recv):
                        b = s.buffer
                        over = b.byte_size() - target
                        if over > 0:
                            k = min(over, len(b.head()))
                            if k:
                                b.evictions += 1
                                b.consume(k, "evict")
        # Delta-sync every live tracker's ledger, then retire the dead.
        for conn, tracker in items:
            with tracker.lock:
                if tracker.ledger:
                    deltas = dict(tracker.ledger)
                    tracker.ledger.clear()
                else:
                    deltas = None
            led.merge_causes(deltas)
        with self._lock:
            for conn, cause in retire:
                tracker = self._trackers.pop(conn, None)
                if tracker is None:
                    continue
                self._protocol.pop(conn, None)
                self._quarantine.pop(conn, None)
                if tracker.quarantined:
                    _QUARANTINED_G.dec()
                self._remember(conn, "closed")
                with tracker.lock:
                    # Seal the tracker: straggler events that raced the
                    # feeder drain to the retirement cause, the final
                    # ledger deltas sync, and `retired` makes any adds
                    # after this point count at the connector instead.
                    tracker.retired = True
                    tracker.drain_all(cause)
                    deltas = dict(tracker.ledger)
                    tracker.ledger.clear()
                led.merge_causes(deltas)
                with led.lock:
                    led.frames_parsed += tracker.frames_parsed()
                    led.frames_stitched += tracker.frames_stitched
                    led.frames_drained += tracker.frames_drained
                    led.records_stitched += tracker.records_stitched
            # Re-anchor the global-bytes estimate exactly.
            total = 0
            for tracker in self._trackers.values():
                with tracker.lock:
                    total += tracker.byte_size()
            self._global_bytes = total
            n_trackers = len(self._trackers)
        self._sync_metrics(n_trackers)

    def _emit_rows(
        self, conn, tracker, records, level: int, body_cap: int
    ) -> None:
        led = self.ledger
        proto = self._protocol[conn]
        table = next(
            t for t in self.tables if t.name == _TABLE_FOR[proto]
        )
        row_fn = _ROW_FNS[proto]
        emitted = capped = truncated = 0
        for rec in records:
            row = row_fn(
                rec,
                tracker.upid,
                tracker.remote_addr,
                tracker.remote_port,
                int(tracker.role),
            )
            if level >= 1:
                # Ladder level 1: bodies shrink before rows land.
                for k, v in row.items():
                    if isinstance(v, str) and len(v) > body_cap:
                        row[k] = v[:body_cap]
                        truncated += 1
            if table.append_record(**row):
                emitted += 1
            else:
                capped += 1
        with led.lock:
            led.rows_emitted += emitted
            led.rows_dropped_table_cap += capped
            led.bodies_truncated += truncated
        if emitted:
            _ROWS.inc(emitted, table=table.name)
        if capped:
            _DROPS.inc(capped, reason="table_cap")

    def _transfer_legacy(self) -> None:
        with self._lock:
            items = list(self._trackers.items())
        for conn, tracker in items:
            with tracker.lock:
                records = tracker.process_to_records()
            if not records:
                continue
            proto = self._protocol[conn]
            table = next(
                t for t in self.tables if t.name == _TABLE_FOR[proto]
            )
            row_fn = _ROW_FNS[proto]
            for rec in records:
                table.append_record(
                    **row_fn(
                        rec,
                        tracker.upid,
                        tracker.remote_addr,
                        tracker.remote_port,
                        int(tracker.role),
                    )
                )
        # GC closed trackers whose buffers are drained (ref: ConnTracker
        # disposal after inactivity).
        with self._lock:
            for conn in [
                c
                for c, t in self._trackers.items()
                if t.closed
                and not t.send.buffer.head()
                and not t.recv.buffer.head()
                and not t.send.frames
                and not t.recv.frames
            ]:
                del self._trackers[conn]
                del self._protocol[conn]

    # -- the push step --------------------------------------------------------
    def push_data(self, push_cb) -> None:
        if not self._robust:
            super().push_data(push_cb)
            return
        led = self.ledger
        stalled = False
        for dt in self.tables:
            data = dt.take()
            if data is None:
                continue
            nrows = len(next(iter(data.values()))) if data else 0
            try:
                if faults.ACTIVE and faults.fires("ingest.push_stall"):
                    raise RuntimeError("injected ingest.push_stall")
                push_cb(dt.name, dt.tablet, data)
            except Exception as e:
                # The rows are gone (take() already cleared the table):
                # count them so conservation stays exact, surface the
                # stall, and force the ladder to level >= 2 next tick.
                stalled = True
                with led.lock:
                    led.rows_dropped_push += nrows
                _DROPS.inc(nrows, reason="push_stall")
                self._record_error(
                    str(e), {"event": "push_stall", "table": dt.name}
                )
                continue
            with led.lock:
                led.rows_pushed += nrows
        self._push_stalled = stalled

    # -- observability --------------------------------------------------------
    def _sync_metrics(self, n_trackers: int) -> None:
        led = self.ledger
        with led.lock:
            events = led.events_fed
            causes = dict(led.causes)
        _EVENTS.inc(max(0, events - self._ev_synced))
        self._ev_synced = events
        synced = self._cause_synced
        for cause, n in causes.items():
            if cause in DROP_CAUSES:
                d = n - synced.get(cause, 0)
                if d > 0:
                    _DROPS.inc(d, reason=cause)
        self._cause_synced = causes
        _TRACKERS_G.set(n_trackers)
        _BUFFER_G.set(self._global_bytes)
        _SHED_G.set(self._shed_level)

    def ingest_status(self) -> dict:
        """Exact accounting snapshot: totals, per-cause attributions,
        and the three conservation laws. At a quiescent point (no feeder
        racing, post transfer+push) every law holds exactly."""
        led = self.ledger
        with self._lock:
            trackers = list(self._trackers.values())
            n_trackers = len(trackers)
            global_bytes = self._global_bytes
        causes: dict[str, int] = {}
        pending_events = 0
        frames_parsed = frames_stitched = frames_drained = 0
        records_stitched = frames_pending = 0
        quarantined = 0
        for t in trackers:
            with t.lock:
                if t.ledger:
                    for cause, n in t.ledger.items():
                        causes[cause] = causes.get(cause, 0) + n
                pending_events += t.events_pending()
                frames_parsed += t.frames_parsed()
                frames_stitched += t.frames_stitched
                frames_drained += t.frames_drained
                records_stitched += t.records_stitched
                frames_pending += t.frames_pending()
                if t.quarantined:
                    quarantined += 1
        with led.lock:
            for cause, n in led.causes.items():
                causes[cause] = causes.get(cause, 0) + n
            events_fed = led.events_fed
            frames_parsed += led.frames_parsed
            frames_stitched += led.frames_stitched
            frames_drained += led.frames_drained
            records_stitched += led.records_stitched
            rows_emitted = led.rows_emitted
            rows_dropped_table_cap = led.rows_dropped_table_cap
            rows_dropped_push = led.rows_dropped_push
            rows_pushed = led.rows_pushed
            extra = {
                "bodies_truncated": led.bodies_truncated,
                "conns_sampled_out": led.conns_sampled_out,
                "quarantine_opens": led.quarantine_opens,
                "leaked_closes": led.leaked_closes,
            }
        rows_pending = sum(t.occupancy for t in self.tables)
        attributed = sum(causes.values())
        return {
            "events_fed": events_fed,
            "causes": causes,
            "events_pending": pending_events,
            "events_attributed": attributed,
            "law_a_ok": events_fed == attributed + pending_events,
            "frames_parsed": frames_parsed,
            "frames_stitched": frames_stitched,
            "frames_drained": frames_drained,
            "frames_pending": frames_pending,
            "law_b_ok": frames_parsed
            == frames_stitched + frames_drained + frames_pending,
            "records_stitched": records_stitched,
            "rows_emitted": rows_emitted,
            "rows_dropped_table_cap": rows_dropped_table_cap,
            "law_c_ok": records_stitched
            == rows_emitted + rows_dropped_table_cap,
            "rows_pushed": rows_pushed,
            "rows_dropped_push": rows_dropped_push,
            "rows_pending": rows_pending,
            "law_push_ok": rows_emitted
            == rows_pushed + rows_dropped_push + rows_pending,
            "trackers": n_trackers,
            "buffer_bytes": global_bytes,
            "shed_level": self._shed_level,
            "quarantined": quarantined,
            **extra,
        }
