"""Socket-trace connector: byte streams → protocol tables.

Ref: socket_trace_connector.h:89 — the reference's flagship connector
attaches eBPF probes, reassembles per-connection byte streams through
ConnTrackers, parses protocol frames, stitches request/response pairs,
and appends rows to per-protocol tables. On TPU hosts the eBPF capture
layer is out of scope (BASELINE: collection stays CPU-side), so this
connector consumes *socket events* — (conn, direction, position, bytes,
timestamp) tuples — from replayed captures or synthetic workloads, and
runs the SAME userspace pipeline: ConnTracker → DataStreamBuffer →
parser → stitcher → http_events / dns_events rows.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Optional

from pixie_tpu.ingest.http_gen import HTTP_EVENTS_REL
from pixie_tpu.ingest.source_connector import DataTable, SourceConnector
from pixie_tpu.protocols import dns as dns_proto
from pixie_tpu.protocols import http as http_proto
from pixie_tpu.protocols import http2 as http2_proto
from pixie_tpu.protocols import mysql as mysql_proto
from pixie_tpu.protocols import pgsql as pgsql_proto
from pixie_tpu.protocols import redis as redis_proto
from pixie_tpu.protocols.base import ConnTracker, TraceRole
from pixie_tpu.types import DataType, Relation, SemanticType

I, S, T = DataType.INT64, DataType.STRING, DataType.TIME64NS

# ref: dns_table.h kDNSElements
DNS_EVENTS_REL = Relation.of(
    ("time_", T, SemanticType.ST_TIME_NS),
    ("upid", S, SemanticType.ST_UPID),
    ("remote_addr", S, SemanticType.ST_IP_ADDRESS),
    ("remote_port", I),
    ("trace_role", I),
    ("req_header", S),
    ("req_body", S),
    ("resp_header", S),
    ("resp_body", S),
    ("latency", I, SemanticType.ST_DURATION_NS),
)

# ref: mysql_table.h kMySQLElements
MYSQL_EVENTS_REL = Relation.of(
    ("time_", T, SemanticType.ST_TIME_NS),
    ("upid", S, SemanticType.ST_UPID),
    ("remote_addr", S, SemanticType.ST_IP_ADDRESS),
    ("remote_port", I),
    ("trace_role", I),
    ("req_cmd", I),
    ("req_body", S),
    ("resp_status", I),
    ("resp_body", S),
    ("latency", I, SemanticType.ST_DURATION_NS),
)

# ref: pgsql_table.h kPGSQLElements
PGSQL_EVENTS_REL = Relation.of(
    ("time_", T, SemanticType.ST_TIME_NS),
    ("upid", S, SemanticType.ST_UPID),
    ("remote_addr", S, SemanticType.ST_IP_ADDRESS),
    ("remote_port", I),
    ("trace_role", I),
    ("req_cmd", S),
    ("req", S),
    ("resp", S),
    ("latency", I, SemanticType.ST_DURATION_NS),
)

# ref: redis_table.h kRedisElements
REDIS_EVENTS_REL = Relation.of(
    ("time_", T, SemanticType.ST_TIME_NS),
    ("upid", S, SemanticType.ST_UPID),
    ("remote_addr", S, SemanticType.ST_IP_ADDRESS),
    ("remote_port", I),
    ("trace_role", I),
    ("req_cmd", S),
    ("req_args", S),
    ("resp", S),
    ("latency", I, SemanticType.ST_DURATION_NS),
)

_PARSERS = {
    "http": http_proto.HttpParser(),
    "http2": http2_proto.Http2Parser(),
    "dns": dns_proto.DnsParser(),
    "mysql": mysql_proto.MysqlParser(),
    "pgsql": pgsql_proto.PgsqlParser(),
    "redis": redis_proto.RedisParser(),
}
_ROW_FNS = {
    "http": http_proto.record_to_row,
    "http2": http_proto.record_to_row,  # gRPC lands in http_events
    "dns": dns_proto.record_to_row,
    "mysql": mysql_proto.record_to_row,
    "pgsql": pgsql_proto.record_to_row,
    "redis": redis_proto.record_to_row,
}
_TABLE_FOR = {
    "http": "http_events",
    "http2": "http_events",
    "dns": "dns_events",
    "mysql": "mysql_events",
    "pgsql": "pgsql_events",
    "redis": "redis_events",
}


@dataclasses.dataclass(frozen=True)
class ConnId:
    """Ref: conn_id_t — (upid, fd, generation) identifies a connection."""

    upid: str
    fd: int
    tsid: int = 0


class SocketTraceConnector(SourceConnector):
    """Drives ConnTrackers from fed socket events (ref:
    SocketTraceConnector::TransferDataImpl iterating conn trackers)."""

    name = "socket_tracer"

    def __init__(self):
        super().__init__()
        self._lock = threading.Lock()
        self._trackers: dict[ConnId, ConnTracker] = {}
        self._protocol: dict[ConnId, str] = {}

    def init_impl(self) -> None:
        self.tables = [
            DataTable("http_events", HTTP_EVENTS_REL),
            DataTable("dns_events", DNS_EVENTS_REL),
            DataTable("mysql_events", MYSQL_EVENTS_REL),
            DataTable("pgsql_events", PGSQL_EVENTS_REL),
            DataTable("redis_events", REDIS_EVENTS_REL),
        ]

    # -- event feed (the capture boundary) -----------------------------------
    def conn_open(
        self,
        conn: ConnId,
        protocol: str,
        role: TraceRole = TraceRole.CLIENT,
        remote_addr: str = "",
        remote_port: int = 0,
    ) -> None:
        if protocol not in _PARSERS:
            raise ValueError(f"unsupported protocol {protocol!r}")
        with self._lock:
            self._trackers[conn] = ConnTracker(
                _PARSERS[protocol],
                upid=conn.upid,
                remote_addr=remote_addr,
                remote_port=remote_port,
                role=role,
            )
            self._protocol[conn] = protocol

    def data_event(
        self,
        conn: ConnId,
        direction: str,  # "send" | "recv"
        pos: int,
        data: bytes,
        timestamp_ns: int,
    ) -> None:
        """One captured chunk (ref: socket_trace.c data events carry
        per-direction byte positions so userspace can reassemble)."""
        with self._lock:
            tracker = self._trackers.get(conn)
        if tracker is None:
            return  # conn never opened (capture raced) — drop, like the ref
        if direction == "send":
            tracker.add_send(pos, data, timestamp_ns)
        else:
            tracker.add_recv(pos, data, timestamp_ns)

    def conn_close(self, conn: ConnId) -> None:
        with self._lock:
            tracker = self._trackers.get(conn)
        if tracker is not None:
            tracker.closed = True

    def replay(self, events) -> None:
        """Feed a sequence of (kind, ...) capture tuples:
        ("open", conn, protocol, role, remote_addr, remote_port),
        ("data", conn, direction, pos, bytes, timestamp_ns),
        ("close", conn)."""
        for ev in events:
            kind = ev[0]
            if kind == "open":
                self.conn_open(*ev[1:])
            elif kind == "data":
                self.data_event(*ev[1:])
            elif kind == "close":
                self.conn_close(ev[1])
            else:
                raise ValueError(f"unknown capture event {kind!r}")

    # -- the sample step ------------------------------------------------------
    def transfer_data_impl(self, ctx) -> None:
        with self._lock:
            items = list(self._trackers.items())
        for conn, tracker in items:
            records = tracker.process_to_records()
            if not records:
                continue
            proto = self._protocol[conn]
            table = next(
                t for t in self.tables if t.name == _TABLE_FOR[proto]
            )
            row_fn = _ROW_FNS[proto]
            for rec in records:
                table.append_record(
                    **row_fn(
                        rec,
                        tracker.upid,
                        tracker.remote_addr,
                        tracker.remote_port,
                        int(tracker.role),
                    )
                )
        # GC closed trackers whose buffers are drained (ref: ConnTracker
        # disposal after inactivity).
        with self._lock:
            for conn in [
                c
                for c, t in self._trackers.items()
                if t.closed
                and not t.send.buffer.head()
                and not t.recv.buffer.head()
                and not t.send.frames
                and not t.recv.frames
            ]:
                del self._trackers[conn]
                del self._protocol[conn]
