"""Telemetry ingest: the Stirling-equivalent collection layer (CPU-side).

Ref: src/stirling/ — Stirling core (stirling.{h,cc}: RegisterDataPushCallback,
GetPublishProto, Run at :91-193; RunCore poll loop at stirling.cc:802-852),
SourceConnector lifecycle (core/source_connector.h:43-80:
Init/InitContext/TransferData/PushData/Stop), per-source sampling/push
FrequencyManager (core/frequency_manager.*), InfoClassManager schema publish
(core/info_class_manager.*, core/pub_sub_manager.*), DataTable with
tabletization (core/data_table.h:51).

BY DESIGN this stays on host CPU (BASELINE: "Stirling's eBPF collection and
the PEM ingest path stay on CPU"). Real eBPF connectors are out of scope on
TPU hosts; the interface matches so they can be added, and the shipped
connectors are the deterministic test source (ref: seq_gen), a synthetic
protocol-trace generator (the load-gen analogue of the socket tracer's
http_events output), and process/network stat samplers reading procfs.
"""

from pixie_tpu.ingest.core import IngestCore
from pixie_tpu.ingest.source_connector import DataTable, SourceConnector
from pixie_tpu.ingest.seq_gen import SeqGenConnector
from pixie_tpu.ingest.http_gen import HTTPEventsConnector
from pixie_tpu.ingest.proc_stats import (
    NetworkStatsConnector,
    ProcessStatsConnector,
)
from pixie_tpu.ingest.self_telemetry import SelfTelemetrySourceConnector

__all__ = [
    "DataTable",
    "HTTPEventsConnector",
    "IngestCore",
    "NetworkStatsConnector",
    "ProcessStatsConnector",
    "SelfTelemetrySourceConnector",
    "SeqGenConnector",
    "SourceConnector",
]
