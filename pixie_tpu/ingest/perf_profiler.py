"""Synthetic continuous-CPU-profiler connector.

Produces the `stack_traces.beta` table with the reference's schema
(ref: src/stirling/source_connectors/perf_profiler/stack_traces_table.h:31
— time_, upid, stack_trace_id, stack_trace, count) so px/perf_flamegraph
(BASELINE config 4) has a data source. The reference samples kernel stack
traces via eBPF and symbolizes them (perf_profile_connector.h:48); on a TPU
host we synthesize folded-format stacks drawn from a fixed call-tree, with
per-(upid, stack) sampled counts per profiling window — the downstream
cross-shard groupby(stack_trace_id).sum(count) merge is what the benchmark
exercises.
"""

from __future__ import annotations

import time

import numpy as np

from pixie_tpu.ingest.source_connector import DataTable, SourceConnector
from pixie_tpu.types import DataType, Relation, SemanticType

I, S, T = DataType.INT64, DataType.STRING, DataType.TIME64NS

# r15: the reference schema plus query-attribution columns — a sampled
# stack taken while its thread worked on behalf of a query (the
# thread-ambient attribution registry in utils/trace.py) carries that
# query's id/tenant/phase; unattributed stacks carry "".
STACK_TRACES_REL = Relation.of(
    ("time_", T, SemanticType.ST_TIME_NS),
    ("upid", S, SemanticType.ST_UPID),
    ("stack_trace_id", I),
    ("stack_trace", S),
    ("count", I),
    ("query_id", S),
    ("tenant", S),
    ("phase", S),
)

# A small synthetic call forest in folded format (semicolon-separated,
# matching the reference's stringifier output).
_FRAMES = [
    "main",
    "main;net.Serve",
    "main;net.Serve;http.HandleRequest",
    "main;net.Serve;http.HandleRequest;json.Decode",
    "main;net.Serve;http.HandleRequest;db.Query",
    "main;net.Serve;http.HandleRequest;db.Query;pgx.Exec",
    "main;runtime.gc",
    "main;runtime.gc;runtime.scanobject",
]


class PerfProfilerConnector(SourceConnector):
    name = "perf_profiler"
    # The reference pushes a profile roughly every 30s; keep the same
    # windowed shape but at test-friendly frequency.
    sample_period_s = 0.05
    push_period_s = 0.1

    def __init__(
        self,
        n_processes: int = 4,
        samples_per_window: int = 1000,
        seed: int = 0,
    ):
        super().__init__()
        self.rng = np.random.default_rng(seed)
        self.upids = np.array(
            [f"1:{100 + i}:{i * 13 + 5}" for i in range(n_processes)],
            dtype=object,
        )
        self.samples_per_window = samples_per_window
        self.stacks = np.array(_FRAMES, dtype=object)
        # Stable ids: the reference caches an id per distinct folded stack
        # (stack_trace_id_cache.h). Use the deterministic FNV-1a content
        # hash — Python's hash() is salted per process, which would split
        # one stack's counts across ids when PEMs restart or differ.
        from pixie_tpu.table.column import _fnv1a64

        self.stack_ids = np.array(
            [np.int64(_fnv1a64(s) >> np.uint64(1)) for s in _FRAMES],
            np.int64,
        )
        # Leaf-heavy sampling distribution (deep frames burn the CPU).
        w = np.array([1, 2, 4, 8, 10, 12, 3, 5], np.float64)
        self.probs = w / w.sum()
        self.tables = [DataTable("stack_traces.beta", STACK_TRACES_REL)]

    def transfer_data_impl(self, ctx) -> None:
        now = time.time_ns()
        rows_t, rows_u, rows_id, rows_s, rows_c = [], [], [], [], []
        for upid in self.upids:
            # Multinomial sample: how many of this window's samples landed
            # in each stack for this process.
            counts = self.rng.multinomial(
                self.samples_per_window, self.probs
            )
            nz = np.nonzero(counts)[0]
            rows_t.append(np.full(len(nz), now, np.int64))
            rows_u.append(np.full(len(nz), upid, dtype=object))
            rows_id.append(self.stack_ids[nz])
            rows_s.append(self.stacks[nz])
            rows_c.append(counts[nz].astype(np.int64))
        n = sum(len(r) for r in rows_t)
        empty = np.full(n, "", dtype=object)
        self.tables[0].append_columns(
            {
                "time_": np.concatenate(rows_t),
                "upid": np.concatenate(rows_u),
                "stack_trace_id": np.concatenate(rows_id),
                "stack_trace": np.concatenate(rows_s),
                "count": np.concatenate(rows_c),
                # Synthetic stacks have no owning query.
                "query_id": empty,
                "tenant": empty,
                "phase": empty,
            }
        )
