"""Self-monitoring: connector status/errors as a queryable table.

Ref: src/stirling/source_connectors/stirling_error/ — the reference
reports each source connector's deployment status and runtime errors into
a `stirling_error` table (stirling_error_table.h:31: time_, upid,
source_connector, status, error, context) so operators debug collection
with the SAME query engine the data flows through. Here the ingest core
records connector init results and transfer_data exceptions; errors stop
being log-only (VERDICT r4 missing #7).
"""

from __future__ import annotations

import json
import os
import time

import numpy as np

from pixie_tpu.ingest.source_connector import DataTable, SourceConnector
from pixie_tpu.types import DataType, Relation, SemanticType

I, S, T = DataType.INT64, DataType.STRING, DataType.TIME64NS

STIRLING_ERROR_REL = Relation.of(
    ("time_", T, SemanticType.ST_TIME_NS),
    ("upid", S, SemanticType.ST_UPID),
    ("source_connector", S),
    ("status", I),
    ("error", S),
    ("context", S),
)

# ref: statuspb codes surfaced in the status column
STATUS_OK = 0
STATUS_ERROR = 2


class StirlingErrorConnector(SourceConnector):
    """Accumulates status records; flushes like any other connector."""

    name = "stirling_error"
    sample_period_s = 0.5
    push_period_s = 0.5

    def __init__(self):
        super().__init__()
        self.tables = [DataTable("stirling_error", STIRLING_ERROR_REL)]
        self._upid = f"1:{os.getpid()}:1"

    def record(
        self,
        source: str,
        status: int,
        error: str = "",
        context: dict | None = None,
    ) -> None:
        self.tables[0].append_columns(
            {
                "time_": np.array([time.time_ns()], np.int64),
                "upid": np.array([self._upid], dtype=object),
                "source_connector": np.array([source], dtype=object),
                "status": np.array([status], np.int64),
                "error": np.array([error], dtype=object),
                "context": np.array(
                    [json.dumps(context or {}, sort_keys=True)], dtype=object
                ),
            }
        )

    def transfer_data_impl(self, ctx) -> None:
        pass  # records are appended by record(); push flushes them
