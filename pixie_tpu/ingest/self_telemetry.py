"""Self-telemetry: the engine's own spans + metrics as queryable tables.

Ref: src/stirling/source_connectors/stirling_error/ and the reference's
`probe_status` table — the engine reports on ITSELF through the same
table/query machinery the observability data flows through. Here two
tables land on every node:

  query_spans     finished trace spans (utils/trace.py): one row per
                  span with trace_id/span_id/parent_id, timings, status,
                  and a JSON attrs blob — `px/query_profile` reconstructs
                  a query's phase breakdown from it.
  engine_metrics  point-in-time samples of the shared MetricsRegistry
                  (counters, gauges, histogram _sum/_count series), so
                  `transport_dedup_dropped_total` and friends are one
                  PxL filter away.

Two consumption paths share ``flush_into``: the periodic
SelfTelemetrySourceConnector (registered in an IngestCore, cadence
``self_telemetry_interval_s``) for PEM deployments, and an on-demand
flush in Carnot.execute_plan when a plan reads either table — a query
that finished microseconds ago is immediately profilable.
"""

from __future__ import annotations

import json
import time

import numpy as np

from pixie_tpu.ingest.source_connector import DataTable, SourceConnector
from pixie_tpu.types import DataType, Relation, SemanticType
from pixie_tpu.utils import metrics_registry, trace
from pixie_tpu.utils.config import define_flag, flags

define_flag(
    "self_telemetry_interval_s",
    1.0,
    help_="Sampling/push period of the self-telemetry source connector "
    "(ingest/self_telemetry.py): how often finished trace spans and "
    "metric samples drain into the node's query_spans/engine_metrics "
    "tables.",
)

I, F, S, T = (
    DataType.INT64,
    DataType.FLOAT64,
    DataType.STRING,
    DataType.TIME64NS,
)

QUERY_SPANS_TABLE = "query_spans"
ENGINE_METRICS_TABLE = "engine_metrics"

QUERY_SPANS_REL = Relation.of(
    ("time_", T, SemanticType.ST_TIME_NS),  # span START time
    ("trace_id", S),
    ("span_id", S),
    ("parent_id", S),
    ("name", S),
    ("instance", S),
    ("status", S),
    ("duration_ns", I),
    ("attrs", S),  # JSON-encoded key/value attributes
)

ENGINE_METRICS_REL = Relation.of(
    ("time_", T, SemanticType.ST_TIME_NS),
    ("name", S),
    ("kind", S),
    ("labels", S),  # JSON-encoded label set
    ("value", F),
)


def ensure_tables(store) -> None:
    """Create the self-telemetry tables in a TableStore when missing."""
    if store.get_table(QUERY_SPANS_TABLE) is None:
        store.create_table(QUERY_SPANS_TABLE, QUERY_SPANS_REL)
    if store.get_table(ENGINE_METRICS_TABLE) is None:
        store.create_table(ENGINE_METRICS_TABLE, ENGINE_METRICS_REL)


def plan_reads_telemetry(plan) -> bool:
    """True when any fragment's memory source reads a self-telemetry
    table (the on-demand flush trigger in Carnot.execute_plan)."""
    from pixie_tpu.plan.operators import MemorySourceOp

    for frag in plan.fragments:
        for nid in frag.nodes():
            op = frag.node(nid)
            if isinstance(op, MemorySourceOp) and op.table_name in (
                QUERY_SPANS_TABLE,
                ENGINE_METRICS_TABLE,
            ):
                return True
    return False


def spans_to_columns(spans) -> dict:
    """Finished spans -> query_spans column dict."""
    return {
        "time_": np.array(
            [s.start_unix_ns for s in spans], np.int64
        ),
        "trace_id": np.array([s.trace_id for s in spans], dtype=object),
        "span_id": np.array([s.span_id for s in spans], dtype=object),
        "parent_id": np.array([s.parent_id for s in spans], dtype=object),
        "name": np.array([s.name for s in spans], dtype=object),
        "instance": np.array([s.instance for s in spans], dtype=object),
        "status": np.array([s.status for s in spans], dtype=object),
        "duration_ns": np.array([s.duration_ns for s in spans], np.int64),
        "attrs": np.array(
            [json.dumps(s.attrs, sort_keys=True, default=str)
             for s in spans],
            dtype=object,
        ),
    }


def metrics_to_columns(now_ns: int) -> dict:
    """One sample row per (metric, label set) from the shared registry.
    Histograms expose their ``_sum``/``_count`` series (bucket vectors
    stay on /metrics where the exposition format carries them)."""
    reg = metrics_registry()
    times, names, kinds, labels, values = [], [], [], [], []

    def add(name, kind, key, value):
        times.append(now_ns)
        names.append(name)
        kinds.append(kind)
        labels.append(json.dumps(dict(key), sort_keys=True))
        values.append(float(value))

    for name, samples in reg.collect().items():
        for key, val in samples.items():
            if isinstance(val, dict):  # histogram state
                add(f"{name}_sum", "histogram", key, val["sum"])
                add(f"{name}_count", "histogram", key, val["count"])
            else:
                add(name, "scalar", key, val)
    return {
        "time_": np.array(times, np.int64),
        "name": np.array(names, dtype=object),
        "kind": np.array(kinds, dtype=object),
        "labels": np.array(labels, dtype=object),
        "value": np.array(values, np.float64),
    }


def flush_into(store, include_metrics: bool = True) -> int:
    """Drain the finished-span buffer (and sample the metrics registry)
    directly into a TableStore's self-telemetry tables. Returns the
    number of span rows written. Shared by the on-demand read path and
    available to embedders that run no IngestCore."""
    ensure_tables(store)
    written = 0
    spans = trace.drain()
    if spans:
        store.get_table(QUERY_SPANS_TABLE).write_pydict(
            spans_to_columns(spans)
        )
        written = len(spans)
    if include_metrics:
        cols = metrics_to_columns(time.time_ns())
        if len(cols["time_"]):
            store.get_table(ENGINE_METRICS_TABLE).write_pydict(cols)
    return written


class SelfTelemetrySourceConnector(SourceConnector):
    """Periodically drains finished spans and metric samples into
    DataTables, pushed like any other connector (ref: stirling_error's
    connector shape)."""

    name = "self_telemetry"

    def __init__(self, interval_s: "float | None" = None):
        period = (
            interval_s
            if interval_s is not None
            else flags.self_telemetry_interval_s
        )
        self.sample_period_s = period
        self.push_period_s = period
        super().__init__()
        self.tables = [
            DataTable(QUERY_SPANS_TABLE, QUERY_SPANS_REL),
            DataTable(ENGINE_METRICS_TABLE, ENGINE_METRICS_REL),
        ]

    def transfer_data_impl(self, ctx) -> None:
        spans = trace.drain()
        if spans:
            self.tables[0].append_columns(spans_to_columns(spans))
        cols = metrics_to_columns(time.time_ns())
        if len(cols["time_"]):
            self.tables[1].append_columns(cols)
