"""Self-telemetry: the engine's own spans + metrics as queryable tables.

Ref: src/stirling/source_connectors/stirling_error/ and the reference's
`probe_status` table — the engine reports on ITSELF through the same
table/query machinery the observability data flows through. Here two
tables land on every node:

  query_spans     finished trace spans (utils/trace.py): one row per
                  span with trace_id/span_id/parent_id, timings, status,
                  and a JSON attrs blob — `px/query_profile` reconstructs
                  a query's phase breakdown from it.
  engine_metrics  point-in-time samples of the shared MetricsRegistry
                  (counters, gauges, histogram _sum/_count series), so
                  `transport_dedup_dropped_total` and friends are one
                  PxL filter away.

plus the resource-attribution plane (r15, parallel/profiler.py +
serving/residency.py + vizier/slo.py):

  device_programs   compiled device programs: signature hash, unit kind,
                    XLA cost analysis (flops/bytes accessed), compile s.
  device_dispatches per-dispatch device wall time and staged/wire bytes,
                    attributed to (query_id, tenant, phase).
  hbm_usage         residency-pool snapshots: pool totals + per-table
                    staged/pinned/ring bytes vs budget.
  alerts            SLO rule transitions (firing/ok) with the observed
                    value, threshold, and window.

Two consumption paths share ``flush_into``: the periodic
SelfTelemetrySourceConnector (registered in an IngestCore, cadence
``self_telemetry_interval_s``) for PEM deployments, and an on-demand
flush in Carnot.execute_plan when a plan reads any of these tables — a
query that finished microseconds ago is immediately profilable, and a
distributed query over them sees every node's freshest rows.
"""

from __future__ import annotations

import json
import time

import numpy as np

from pixie_tpu.ingest.source_connector import DataTable, SourceConnector
from pixie_tpu.types import DataType, Relation, SemanticType
from pixie_tpu.utils import metrics_registry, trace
from pixie_tpu.utils.config import define_flag, flags

define_flag(
    "self_telemetry_interval_s",
    1.0,
    help_="Sampling/push period of the self-telemetry source connector "
    "(ingest/self_telemetry.py): how often finished trace spans and "
    "metric samples drain into the node's query_spans/engine_metrics "
    "tables.",
)

I, F, S, T = (
    DataType.INT64,
    DataType.FLOAT64,
    DataType.STRING,
    DataType.TIME64NS,
)

QUERY_SPANS_TABLE = "query_spans"
ENGINE_METRICS_TABLE = "engine_metrics"

QUERY_SPANS_REL = Relation.of(
    ("time_", T, SemanticType.ST_TIME_NS),  # span START time
    ("trace_id", S),
    ("span_id", S),
    ("parent_id", S),
    ("name", S),
    ("instance", S),
    ("status", S),
    ("duration_ns", I),
    ("attrs", S),  # JSON-encoded key/value attributes
)

ENGINE_METRICS_REL = Relation.of(
    ("time_", T, SemanticType.ST_TIME_NS),
    ("name", S),
    ("kind", S),
    ("labels", S),  # JSON-encoded label set
    ("value", F),
)

DEVICE_PROGRAMS_TABLE = "device_programs"
DEVICE_DISPATCHES_TABLE = "device_dispatches"
HBM_USAGE_TABLE = "hbm_usage"
ALERTS_TABLE = "alerts"

DEVICE_PROGRAMS_REL = Relation.of(
    ("time_", T, SemanticType.ST_TIME_NS),
    ("program", S),  # kind:contenthash (parallel/profiler.program_name)
    ("kind", S),     # init | fold | merge | fin | decode | ...
    ("flops", F),
    ("bytes_accessed", F),
    ("compile_seconds", F),
)

DEVICE_DISPATCHES_REL = Relation.of(
    ("time_", T, SemanticType.ST_TIME_NS),
    ("query_id", S),
    ("tenant", S),
    ("phase", S),
    ("kind", S),  # fold | stream_fold | stream_window
    ("program", S),
    ("duration_ns", I),
    ("rows", I),
    ("staged_bytes", I),
    ("wire_bytes", I),
)

HBM_USAGE_REL = Relation.of(
    ("time_", T, SemanticType.ST_TIME_NS),
    ("scope", S),  # pool | table
    ("name", S),   # "" for the pool row, else the table name
    ("used_bytes", I),
    ("pinned_bytes", I),
    ("resident_bytes", I),
    ("budget_bytes", I),
    ("entries", I),
)

ALERTS_REL = Relation.of(
    ("time_", T, SemanticType.ST_TIME_NS),
    ("rule", S),
    ("state", S),  # firing | ok
    ("severity", S),
    ("value", F),
    ("threshold", F),
    ("tenant", S),
    ("window_s", F),
    ("detail", S),
)

_ALL_TABLES = (
    (QUERY_SPANS_TABLE, QUERY_SPANS_REL),
    (ENGINE_METRICS_TABLE, ENGINE_METRICS_REL),
    (DEVICE_PROGRAMS_TABLE, DEVICE_PROGRAMS_REL),
    (DEVICE_DISPATCHES_TABLE, DEVICE_DISPATCHES_REL),
    (HBM_USAGE_TABLE, HBM_USAGE_REL),
    (ALERTS_TABLE, ALERTS_REL),
)


def ensure_tables(store) -> None:
    """Create the self-telemetry tables in a TableStore when missing."""
    for name, rel in _ALL_TABLES:
        if store.get_table(name) is None:
            store.create_table(name, rel)


def plan_reads_telemetry(plan) -> bool:
    """True when any fragment's memory source reads a self-telemetry
    table (the on-demand flush trigger in Carnot.execute_plan)."""
    from pixie_tpu.plan.operators import MemorySourceOp

    names = {name for name, _ in _ALL_TABLES}
    for frag in plan.fragments:
        for nid in frag.nodes():
            op = frag.node(nid)
            if isinstance(op, MemorySourceOp) and op.table_name in names:
                return True
    return False


def spans_to_columns(spans) -> dict:
    """Finished spans -> query_spans column dict."""
    return {
        "time_": np.array(
            [s.start_unix_ns for s in spans], np.int64
        ),
        "trace_id": np.array([s.trace_id for s in spans], dtype=object),
        "span_id": np.array([s.span_id for s in spans], dtype=object),
        "parent_id": np.array([s.parent_id for s in spans], dtype=object),
        "name": np.array([s.name for s in spans], dtype=object),
        "instance": np.array([s.instance for s in spans], dtype=object),
        "status": np.array([s.status for s in spans], dtype=object),
        "duration_ns": np.array([s.duration_ns for s in spans], np.int64),
        "attrs": np.array(
            [json.dumps(s.attrs, sort_keys=True, default=str)
             for s in spans],
            dtype=object,
        ),
    }


def metrics_to_columns(now_ns: int) -> dict:
    """One sample row per (metric, label set) from the shared registry.
    Histograms expose their ``_sum``/``_count`` series (bucket vectors
    stay on /metrics where the exposition format carries them)."""
    reg = metrics_registry()
    times, names, kinds, labels, values = [], [], [], [], []

    def add(name, kind, key, value):
        times.append(now_ns)
        names.append(name)
        kinds.append(kind)
        labels.append(json.dumps(dict(key), sort_keys=True))
        values.append(float(value))

    for name, samples in reg.collect().items():
        for key, val in samples.items():
            if isinstance(val, dict):  # histogram state
                add(f"{name}_sum", "histogram", key, val["sum"])
                add(f"{name}_count", "histogram", key, val["count"])
            else:
                add(name, "scalar", key, val)
    return {
        "time_": np.array(times, np.int64),
        "name": np.array(names, dtype=object),
        "kind": np.array(kinds, dtype=object),
        "labels": np.array(labels, dtype=object),
        "value": np.array(values, np.float64),
    }


def _rows_to_columns(rows: list, relation) -> dict:
    """Profiler/alert row dicts -> column dict for ``relation``. Rows
    carry ``time_ns``; every other relation column maps by name, with a
    type-appropriate default for missing keys."""
    out = {}
    for c in relation:
        if c.name == "time_":
            out["time_"] = np.array([r["time_ns"] for r in rows], np.int64)
        elif c.data_type == DataType.STRING:
            out[c.name] = np.array(
                [str(r.get(c.name, "")) for r in rows], dtype=object
            )
        elif c.data_type == DataType.FLOAT64:
            out[c.name] = np.array(
                [float(r.get(c.name, 0.0)) for r in rows], np.float64
            )
        else:
            out[c.name] = np.array(
                [int(r.get(c.name, 0)) for r in rows], np.int64
            )
    return out


def _flush_attribution(store) -> int:
    """Drain the resource-attribution buffers (parallel/profiler.py)
    into device_programs/device_dispatches/hbm_usage — forcing one HBM
    snapshot per registered pool first so the usage series is fresh even
    when no pool mutation happened since the last flush."""
    from pixie_tpu.parallel import profiler

    if not profiler.ACTIVE:
        return 0
    profiler.sample_pools()
    written = 0
    for table, rel, rows in (
        (DEVICE_PROGRAMS_TABLE, DEVICE_PROGRAMS_REL,
         profiler.drain_programs()),
        (DEVICE_DISPATCHES_TABLE, DEVICE_DISPATCHES_REL,
         profiler.drain_dispatches()),
        (HBM_USAGE_TABLE, HBM_USAGE_REL, profiler.drain_hbm()),
    ):
        if rows:
            store.get_table(table).write_pydict(
                _rows_to_columns(rows, rel)
            )
            written += len(rows)
    return written


def _flush_alerts(store) -> int:
    """Drain buffered SLO alert transitions (vizier/slo.py) into the
    alerts table."""
    try:
        from pixie_tpu.vizier import slo
    except Exception:  # pragma: no cover - slo layer absent
        return 0
    rows = slo.drain_alert_rows()
    if rows:
        store.get_table(ALERTS_TABLE).write_pydict(
            _rows_to_columns(rows, ALERTS_REL)
        )
    return len(rows)


def flush_into(store, include_metrics: bool = True) -> int:
    """Drain the finished-span buffer, the resource-attribution buffers,
    and pending SLO alerts (and sample the metrics registry) directly
    into a TableStore's self-telemetry tables. Returns the number of
    span rows written. Shared by the on-demand read path and available
    to embedders that run no IngestCore."""
    ensure_tables(store)
    written = 0
    spans = trace.drain()
    if spans:
        store.get_table(QUERY_SPANS_TABLE).write_pydict(
            spans_to_columns(spans)
        )
        written = len(spans)
    if flags.resource_attribution:
        _flush_attribution(store)
    _flush_alerts(store)
    if include_metrics:
        cols = metrics_to_columns(time.time_ns())
        if len(cols["time_"]):
            store.get_table(ENGINE_METRICS_TABLE).write_pydict(cols)
    return written


class SelfTelemetrySourceConnector(SourceConnector):
    """Periodically drains finished spans and metric samples into
    DataTables, pushed like any other connector (ref: stirling_error's
    connector shape)."""

    name = "self_telemetry"

    def __init__(self, interval_s: "float | None" = None):
        period = (
            interval_s
            if interval_s is not None
            else flags.self_telemetry_interval_s
        )
        self.sample_period_s = period
        self.push_period_s = period
        super().__init__()
        self.tables = [
            DataTable(name, rel) for name, rel in _ALL_TABLES
        ]
        self._by_name = {dt.name: dt for dt in self.tables}

    def transfer_data_impl(self, ctx) -> None:
        spans = trace.drain()
        if spans:
            self._by_name[QUERY_SPANS_TABLE].append_columns(
                spans_to_columns(spans)
            )
        if flags.resource_attribution:
            from pixie_tpu.parallel import profiler

            if profiler.ACTIVE:
                profiler.sample_pools()
                for table, rel, rows in (
                    (DEVICE_PROGRAMS_TABLE, DEVICE_PROGRAMS_REL,
                     profiler.drain_programs()),
                    (DEVICE_DISPATCHES_TABLE, DEVICE_DISPATCHES_REL,
                     profiler.drain_dispatches()),
                    (HBM_USAGE_TABLE, HBM_USAGE_REL, profiler.drain_hbm()),
                ):
                    if rows:
                        self._by_name[table].append_columns(
                            _rows_to_columns(rows, rel)
                        )
        try:
            from pixie_tpu.vizier import slo

            rows = slo.drain_alert_rows()
        except Exception:  # pragma: no cover - slo layer absent
            rows = []
        if rows:
            self._by_name[ALERTS_TABLE].append_columns(
                _rows_to_columns(rows, ALERTS_REL)
            )
        cols = metrics_to_columns(time.time_ns())
        if len(cols["time_"]):
            self._by_name[ENGINE_METRICS_TABLE].append_columns(cols)
