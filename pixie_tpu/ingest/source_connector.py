"""SourceConnector base + DataTable + frequency management.

Ref: src/stirling/core/source_connector.h:43-80 (lifecycle), data_table.h:51
(DataTable buffers records between transfer and push, with tabletization),
frequency_manager.* (independent sampling vs push periods per source).
"""

from __future__ import annotations

import time
from typing import Optional

import numpy as np

from pixie_tpu.table.row_batch import RowBatch
from pixie_tpu.types import Relation


class DataTable:
    """Buffers appended records between TransferData and PushData
    (ref: core/data_table.h:51; occupancy-based push thresholds)."""

    def __init__(
        self,
        name: str,
        relation: Relation,
        tablet: str = "",
        max_pending_rows: Optional[int] = None,
    ):
        self.name = name
        self.relation = relation
        self.tablet = tablet
        self._pending: dict[str, list] = {c.name: [] for c in relation}
        self._rows = 0
        # r24 bounded memory: rows buffered between transfer and push may
        # never exceed this cap (None = unbounded legacy behavior). A
        # rejected append returns False and counts in dropped_rows so the
        # connector can attribute it (ledger cause 'table_cap').
        self.max_pending_rows = max_pending_rows
        self.dropped_rows = 0

    def append_record(self, **values) -> bool:
        if (
            self.max_pending_rows is not None
            and self._rows >= self.max_pending_rows
        ):
            self.dropped_rows += 1
            return False
        for c in self.relation:
            self._pending[c.name].append(values[c.name])
        self._rows += 1
        return True

    def append_columns(self, data: dict) -> None:
        n = len(next(iter(data.values())))
        for c in self.relation:
            vals = data[c.name]
            assert len(vals) == n
            self._pending[c.name].extend(
                vals.tolist() if isinstance(vals, np.ndarray) else vals
            )
        self._rows += n

    @property
    def occupancy(self) -> int:
        return self._rows

    def take(self) -> Optional[dict]:
        if not self._rows:
            return None
        out = {k: v for k, v in self._pending.items()}
        self._pending = {c.name: [] for c in self.relation}
        self._rows = 0
        return out


class FrequencyManager:
    """Tracks next-expiry for a periodic action (core/frequency_manager.*)."""

    def __init__(self, period_s: float):
        self.period_s = period_s
        self._next = time.monotonic()

    def expired(self, now: float) -> bool:
        return now >= self._next

    def reset(self, now: float) -> None:
        self._next = now + self.period_s

    def next_expiry(self) -> float:
        return self._next


class SourceConnector:
    """Base connector (ref: core/source_connector.h:43).

    Subclasses define ``tables`` (DataTable list) and implement
    ``transfer_data_impl(ctx)`` appending records into them.
    """

    name = "source"
    sample_period_s = 0.1  # ref: sampling freq per source
    push_period_s = 0.5    # ref: push freq per source

    def __init__(self):
        self.tables: list[DataTable] = []
        self._sample_mgr = FrequencyManager(self.sample_period_s)
        self._push_mgr = FrequencyManager(self.push_period_s)
        self._initialized = False
        # Optional callback(source, status, error, context) wired by
        # IngestCore.run() to the stirling_error connector so sources can
        # surface recoverable faults as queryable rows (r24).
        self.error_recorder = None

    # -- lifecycle ----------------------------------------------------------
    def init(self) -> None:
        """ref: SourceConnector::Init."""
        self.init_impl()
        self._initialized = True

    def stop(self) -> None:
        """ref: SourceConnector::Stop."""
        self.stop_impl()
        self._initialized = False

    def init_impl(self) -> None:
        pass

    def stop_impl(self) -> None:
        pass

    # -- data path ----------------------------------------------------------
    def transfer_data(self, ctx=None) -> None:
        """Sample sources into DataTables (ref: TransferData,
        stirling.cc:837)."""
        assert self._initialized, f"{self.name}: transfer before init"
        self.transfer_data_impl(ctx)

    def transfer_data_impl(self, ctx) -> None:
        raise NotImplementedError

    def push_data(self, push_cb) -> None:
        """Flush DataTables through the registered callback (ref: PushData,
        stirling.cc:841 → DataPushCallback)."""
        for dt in self.tables:
            data = dt.take()
            if data is not None:
                push_cb(dt.name, dt.tablet, data)

    # -- scheduling ---------------------------------------------------------
    def sampling_expired(self, now: float) -> bool:
        return self._sample_mgr.expired(now)

    def push_expired(self, now: float) -> bool:
        return self._push_mgr.expired(now)

    def reset_sample(self, now: float) -> None:
        self._sample_mgr.reset(now)

    def reset_push(self, now: float) -> None:
        self._push_mgr.reset(now)

    def next_tick(self) -> float:
        return min(self._sample_mgr.next_expiry(), self._push_mgr.next_expiry())
