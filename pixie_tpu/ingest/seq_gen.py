"""Deterministic sequence-generator connector (for core-loop tests).

Ref: src/stirling/source_connectors/seq_gen/ — produces predictable
sequences so the Stirling core loop is testable without kernel access
(used by core/stirling_test.cc).
"""

from __future__ import annotations

import time

from pixie_tpu.ingest.source_connector import DataTable, SourceConnector
from pixie_tpu.types import DataType, Relation

I, F, T = DataType.INT64, DataType.FLOAT64, DataType.TIME64NS

SEQ_REL = Relation.of(
    ("time_", T),
    ("x", I),          # linear sequence
    ("xmod10", I),     # x % 10
    ("xsquared", I),   # x*x
    ("fibonnaci", I),  # matches the reference's (misspelled) column
    ("pi", F),
)


def _fib(n: int) -> int:
    a, b = 0, 1
    for _ in range(n):
        a, b = b, a + b
    return a


class SeqGenConnector(SourceConnector):
    name = "seq_gen"
    sample_period_s = 0.01
    push_period_s = 0.05

    def __init__(self, rows_per_sample: int = 10):
        super().__init__()
        self.rows_per_sample = rows_per_sample
        self._x = 0
        self.tables = [DataTable("sequences", SEQ_REL)]

    def transfer_data_impl(self, ctx) -> None:
        dt = self.tables[0]
        now = time.time_ns()
        for i in range(self.rows_per_sample):
            x = self._x
            dt.append_record(
                time_=now + i,
                x=x,
                xmod10=x % 10,
                xsquared=x * x,
                fibonnaci=_fib(x % 64),
                pi=3.141592653589793,
            )
            self._x += 1
