"""Real host CPU profiler: sampled stacks of live processes.

Produces `stack_traces.beta` rows (the reference's schema,
src/stirling/source_connectors/perf_profiler/stack_traces_table.h:31)
from ACTUAL stack samples — the reference samples kernel+user stacks via
eBPF perf events (perf_profile_connector.h:48); without eBPF on a TPU
host this samples two real sources:

- THIS process's Python threads via sys._current_frames() — full user
  stacks of the engine/agents, folded "module.func;module.func" exactly
  like the reference's symbolized output.
- Other live processes' kernel stacks via /proc/<pid>/stack (root-only,
  best-effort) with /proc/<pid>/stat CPU-delta weighting — processes
  that burned CPU since the last sample contribute their current kernel
  stack, so the flamegraph reflects real machine activity.

Counts accumulate per (upid, folded stack) within a push window and
flush on transfer (ref: the profiler's dual-buffer sampling windows).
"""

from __future__ import annotations

import os
import sys
import threading
import time

import numpy as np

from pixie_tpu.ingest.perf_profiler import STACK_TRACES_REL
from pixie_tpu.ingest.source_connector import DataTable, SourceConnector
from pixie_tpu.table.column import _fnv1a64
from pixie_tpu.utils import trace

_NO_ATTR = ("", "", "")


def _fold_python_frame(frame) -> str:
    """Innermost-last folded stack for one Python frame chain."""
    parts: list[str] = []
    depth = 0
    while frame is not None and depth < 64:
        code = frame.f_code
        mod = code.co_filename.rsplit("/", 1)[-1].removesuffix(".py")
        parts.append(f"{mod}.{code.co_name}")
        frame = frame.f_back
        depth += 1
    return ";".join(reversed(parts))


def sample_own_python_stacks(
    skip_ident: "int | None" = None,
) -> "dict[tuple, int]":
    """One sample of every live Python thread's stack ->
    {(folded, query_id, tenant, phase): 1}.

    Attribution (r15): ``sys._current_frames()`` is keyed by thread
    ident, and so is the thread-attribution registry in utils/trace.py —
    a thread sampled while inside a ``trace.attribution(...)`` scope
    (broker/agent execute paths, pack/encode/compile workers via
    ``trace.attributed``) labels its stack with the query it was
    serving; everything else samples with empty attribution, exactly as
    before."""
    attrs = trace.thread_attributions()
    out: "dict[tuple, int]" = {}
    for tid, frames in sys._current_frames().items():
        if tid == skip_ident:
            continue
        folded = _fold_python_frame(frames)
        if folded:
            key = (folded,) + attrs.get(tid, _NO_ATTR)
            out[key] = out.get(key, 0) + 1
    return out


def _read_proc_stack(pid: int) -> str:
    """Folded kernel stack of a process from /proc/<pid>/stack (root)."""
    try:
        with open(f"/proc/{pid}/stack") as f:
            raw = f.read()
    except OSError:
        return ""
    frames = []
    for line in raw.splitlines():
        # "[<0>] ep_poll+0x38c/0x3c0" -> "ep_poll"
        sym = line.split("] ", 1)[-1].split("+", 1)[0].strip()
        if sym and sym != "0xffffffffffffffff":
            frames.append(sym)
    return ";".join(reversed(frames))


def _proc_cpu_ticks(pid: int):
    try:
        with open(f"/proc/{pid}/stat", "rb") as f:
            parts = f.read().rsplit(b") ", 1)[-1].split()
        return int(parts[11]) + int(parts[12])  # utime + stime
    except (OSError, IndexError, ValueError):
        return None


class HostProfilerConnector(SourceConnector):
    """Samples real stacks into stack_traces.beta (folded format)."""

    name = "host_profiler"
    sample_period_s = 0.01  # ~100Hz, the reference's default headroom
    push_period_s = 0.5

    def __init__(
        self,
        sample_others: bool = True,
        max_procs: int = 64,
        skip_self: bool = False,
    ):
        """``skip_self`` excludes the thread CALLING sample() from its
        own samples (a dedicated sampling thread observing the process
        should not profile the observer; default off — the r5 contract
        where an in-thread sample sees its own stack is unchanged)."""
        super().__init__()
        self.tables = [DataTable("stack_traces.beta", STACK_TRACES_REL)]
        # (upid, folded, query_id, tenant, phase) -> sample count
        self._counts: dict[tuple, int] = {}
        self._lock = threading.Lock()
        self._own_upid = f"1:{os.getpid()}:1"
        self._sample_others = sample_others
        self._max_procs = max_procs
        self._skip_self = skip_self
        self._last_ticks: dict[int, int] = {}

    # -- the sample step (called by the ingest core at sample_period) -------
    def sample(self) -> None:
        own = sample_own_python_stacks(
            threading.get_ident() if self._skip_self else None
        )
        with self._lock:
            for (folded, qid, tenant, phase), c in own.items():
                key = (self._own_upid, folded, qid, tenant, phase)
                self._counts[key] = self._counts.get(key, 0) + c
        if self._sample_others:
            self._sample_other_processes()

    def _sample_other_processes(self) -> None:
        me = os.getpid()
        seen = 0
        for entry in os.listdir("/proc"):
            if not entry.isdigit() or int(entry) == me:
                continue
            pid = int(entry)
            ticks = _proc_cpu_ticks(pid)
            if ticks is None:
                continue
            prev = self._last_ticks.get(pid)
            self._last_ticks[pid] = ticks
            if prev is None or ticks <= prev:
                continue  # no CPU burned since last sample
            folded = _read_proc_stack(pid)
            if not folded:
                continue
            # Other processes are outside the engine: no attribution.
            key = (f"1:{pid}:1", folded, "", "", "")
            with self._lock:
                self._counts[key] = self._counts.get(key, 0) + (
                    ticks - prev
                )
            seen += 1
            if seen >= self._max_procs:
                break

    def transfer_data_impl(self, ctx) -> None:
        self.sample()  # at least one sample per push window
        with self._lock:
            counts, self._counts = self._counts, {}
        if not counts:
            return
        now = time.time_ns()
        upids, stacks, ids, cnts = [], [], [], []
        qids, tenants, phases = [], [], []
        for (upid, folded, qid, tenant, phase), c in counts.items():
            upids.append(upid)
            stacks.append(folded)
            ids.append(np.int64(_fnv1a64(folded) >> np.uint64(1)))
            cnts.append(c)
            qids.append(qid)
            tenants.append(tenant)
            phases.append(phase)
        n = len(upids)
        self.tables[0].append_columns(
            {
                "time_": np.full(n, now, np.int64),
                "upid": np.array(upids, dtype=object),
                "stack_trace_id": np.array(ids, np.int64),
                "stack_trace": np.array(stacks, dtype=object),
                "count": np.array(cnts, np.int64),
                "query_id": np.array(qids, dtype=object),
                "tenant": np.array(tenants, dtype=object),
                "phase": np.array(phases, dtype=object),
            }
        )
