"""Process/network stats connectors reading procfs (real host telemetry).

Ref: src/stirling/source_connectors/process_stats/ (265 LoC) and
network_stats/ (284 LoC) — per-process CPU/memory counters resolved against
metadata, and host-level network interface counters. These read the same
/proc files the reference's proc_parser does
(src/common/system/proc_parser.*), so they produce REAL telemetry on any
Linux host without eBPF.
"""

from __future__ import annotations

import os
import time

from pixie_tpu.ingest.source_connector import DataTable, SourceConnector
from pixie_tpu.types import DataType, Relation, SemanticType

I, F, S, T = (
    DataType.INT64,
    DataType.FLOAT64,
    DataType.STRING,
    DataType.TIME64NS,
)

PROCESS_STATS_REL = Relation.of(
    ("time_", T, SemanticType.ST_TIME_NS),
    ("upid", S, SemanticType.ST_UPID),
    ("cmdline", S),
    ("utime_ticks", I),
    ("stime_ticks", I),
    ("rss_bytes", I, SemanticType.ST_BYTES),
    ("vsize_bytes", I, SemanticType.ST_BYTES),
)

NETWORK_STATS_REL = Relation.of(
    ("time_", T, SemanticType.ST_TIME_NS),
    ("interface", S),
    ("rx_bytes", I, SemanticType.ST_BYTES),
    ("rx_packets", I),
    ("tx_bytes", I, SemanticType.ST_BYTES),
    ("tx_packets", I),
)


class ProcessStatsConnector(SourceConnector):
    """Samples /proc/<pid>/stat + statm (ref: process_stats connector +
    proc_parser.cc ParseProcPIDStat)."""

    name = "process_stats"
    sample_period_s = 1.0
    push_period_s = 2.0

    def __init__(self, asid: int = 0, max_pids: int = 512):
        super().__init__()
        self.asid = asid
        self.max_pids = max_pids
        self.tables = [DataTable("process_stats", PROCESS_STATS_REL)]
        self._page_size = os.sysconf("SC_PAGE_SIZE")

    def transfer_data_impl(self, ctx) -> None:
        dt = self.tables[0]
        now = time.time_ns()
        count = 0
        for entry in os.listdir("/proc"):
            if not entry.isdigit():
                continue
            if count >= self.max_pids:
                break
            pid = int(entry)
            try:
                with open(f"/proc/{pid}/stat") as f:
                    stat = f.read()
                with open(f"/proc/{pid}/cmdline", "rb") as f:
                    cmdline = (
                        f.read().replace(b"\x00", b" ").decode(errors="replace").strip()
                    )
                # comm may contain spaces/parens; split after the last ')'.
                rest = stat.rsplit(")", 1)[1].split()
                with open(f"/proc/{pid}/statm") as f:
                    statm = f.read().split()
            except (FileNotFoundError, ProcessLookupError, PermissionError):
                continue
            start_ticks = int(rest[19])  # starttime: stable UPID component
            dt.append_record(
                time_=now,
                upid=f"{self.asid}:{pid}:{start_ticks}",
                cmdline=cmdline or "[kernel]",
                utime_ticks=int(rest[11]),
                stime_ticks=int(rest[12]),
                rss_bytes=int(statm[1]) * self._page_size,
                vsize_bytes=int(rest[20]),
            )
            count += 1


class NetworkStatsConnector(SourceConnector):
    """Samples /proc/net/dev (ref: network_stats connector)."""

    name = "network_stats"
    sample_period_s = 1.0
    push_period_s = 2.0

    def __init__(self):
        super().__init__()
        self.tables = [DataTable("network_stats", NETWORK_STATS_REL)]

    def transfer_data_impl(self, ctx) -> None:
        dt = self.tables[0]
        now = time.time_ns()
        try:
            with open("/proc/net/dev") as f:
                lines = f.readlines()[2:]
        except FileNotFoundError:  # pragma: no cover - non-Linux
            return
        for line in lines:
            iface, _, rest = line.partition(":")
            fields = rest.split()
            if len(fields) < 12:
                continue
            dt.append_record(
                time_=now,
                interface=iface.strip(),
                rx_bytes=int(fields[0]),
                rx_packets=int(fields[1]),
                tx_bytes=int(fields[8]),
                tx_packets=int(fields[9]),
            )
