"""Process/network stats connectors reading procfs (real host telemetry).

Ref: src/stirling/source_connectors/process_stats/ (265 LoC) and
network_stats/ (284 LoC) — per-process CPU/memory/IO counters and per-pod
network counters. Column schemas match the reference tables exactly
(process_stats_table.h kProcessStatsElements, network_stats_table.h
kNetworkStatsElements) so the px/ script library (pods, nodes,
namespaces, upids, pod_memory_usage, ...) runs unchanged. These read the
same /proc files the reference's proc_parser does
(src/common/system/proc_parser.*), so they produce REAL telemetry on any
Linux host without eBPF.
"""

from __future__ import annotations

import os
import time

from pixie_tpu.ingest.source_connector import DataTable, SourceConnector
from pixie_tpu.types import DataType, Relation, SemanticType

I, F, S, T = (
    DataType.INT64,
    DataType.FLOAT64,
    DataType.STRING,
    DataType.TIME64NS,
)

# ref: process_stats_table.h kProcessStatsElements (column-for-column)
PROCESS_STATS_REL = Relation.of(
    ("time_", T, SemanticType.ST_TIME_NS),
    ("upid", S, SemanticType.ST_UPID),
    ("major_faults", I),
    ("minor_faults", I),
    ("cpu_utime_ns", I, SemanticType.ST_DURATION_NS),
    ("cpu_ktime_ns", I, SemanticType.ST_DURATION_NS),
    ("num_threads", I),
    ("vsize_bytes", I, SemanticType.ST_BYTES),
    ("rss_bytes", I, SemanticType.ST_BYTES),
    ("rchar_bytes", I, SemanticType.ST_BYTES),
    ("wchar_bytes", I, SemanticType.ST_BYTES),
    ("read_bytes", I, SemanticType.ST_BYTES),
    ("write_bytes", I, SemanticType.ST_BYTES),
)

# ref: network_stats_table.h kNetworkStatsElements (pod-scoped counters)
NETWORK_STATS_REL = Relation.of(
    ("time_", T, SemanticType.ST_TIME_NS),
    ("pod_id", S),
    ("rx_bytes", I, SemanticType.ST_BYTES),
    ("rx_packets", I),
    ("rx_errors", I),
    ("rx_drops", I),
    ("tx_bytes", I, SemanticType.ST_BYTES),
    ("tx_packets", I),
    ("tx_errors", I),
    ("tx_drops", I),
)


class ProcessStatsConnector(SourceConnector):
    """Samples /proc/<pid>/{stat,statm,io} (ref: process_stats connector +
    proc_parser.cc ParseProcPIDStat/ParseProcPIDStatIO)."""

    name = "process_stats"
    sample_period_s = 1.0
    push_period_s = 2.0

    def __init__(self, asid: int = 0, max_pids: int = 512):
        super().__init__()
        self.asid = asid
        self.max_pids = max_pids
        self.tables = [DataTable("process_stats", PROCESS_STATS_REL)]
        self._page_size = os.sysconf("SC_PAGE_SIZE")
        self._ns_per_tick = 1_000_000_000 // os.sysconf("SC_CLK_TCK")

    def transfer_data_impl(self, ctx) -> None:
        dt = self.tables[0]
        now = time.time_ns()
        count = 0
        for entry in os.listdir("/proc"):
            if not entry.isdigit():
                continue
            if count >= self.max_pids:
                break
            pid = int(entry)
            try:
                with open(f"/proc/{pid}/stat") as f:
                    stat = f.read()
                # comm may contain spaces/parens; split after the last ')'.
                rest = stat.rsplit(")", 1)[1].split()
                with open(f"/proc/{pid}/statm") as f:
                    statm = f.read().split()
            except (FileNotFoundError, ProcessLookupError, PermissionError):
                continue
            io = {}
            try:
                with open(f"/proc/{pid}/io") as f:
                    for line in f:
                        k, _, v = line.partition(":")
                        io[k.strip()] = int(v)
            except (OSError, ValueError):
                pass  # /proc/<pid>/io needs privileges for other users
            start_ticks = int(rest[19])  # starttime: stable UPID component
            dt.append_record(
                time_=now,
                upid=f"{self.asid}:{pid}:{start_ticks}",
                major_faults=int(rest[9]),
                minor_faults=int(rest[7]),
                cpu_utime_ns=int(rest[11]) * self._ns_per_tick,
                cpu_ktime_ns=int(rest[12]) * self._ns_per_tick,
                num_threads=int(rest[17]),
                vsize_bytes=int(rest[20]),
                rss_bytes=int(statm[1]) * self._page_size,
                rchar_bytes=io.get("rchar", 0),
                wchar_bytes=io.get("wchar", 0),
                read_bytes=io.get("read_bytes", 0),
                write_bytes=io.get("write_bytes", 0),
            )
            count += 1


class NetworkStatsConnector(SourceConnector):
    """Samples /proc/net/dev (ref: network_stats connector). The reference
    attributes counters to pods via each pod's network namespace; without
    a cluster the host's interfaces aggregate under the node's pod_id
    ('' when unmapped)."""

    name = "network_stats"
    sample_period_s = 1.0
    push_period_s = 2.0

    def __init__(self, pod_id: str = ""):
        super().__init__()
        self.pod_id = pod_id
        self.tables = [DataTable("network_stats", NETWORK_STATS_REL)]

    def transfer_data_impl(self, ctx) -> None:
        dt = self.tables[0]
        now = time.time_ns()
        try:
            with open("/proc/net/dev") as f:
                lines = f.readlines()[2:]
        except FileNotFoundError:  # pragma: no cover - non-Linux
            return
        rx_b = rx_p = rx_e = rx_d = tx_b = tx_p = tx_e = tx_d = 0
        for line in lines:
            iface, _, rest = line.partition(":")
            fields = rest.split()
            if len(fields) < 12 or iface.strip() == "lo":
                continue
            rx_b += int(fields[0])
            rx_p += int(fields[1])
            rx_e += int(fields[2])
            rx_d += int(fields[3])
            tx_b += int(fields[8])
            tx_p += int(fields[9])
            tx_e += int(fields[10])
            tx_d += int(fields[11])
        dt.append_record(
            time_=now,
            pod_id=self.pod_id,
            rx_bytes=rx_b,
            rx_packets=rx_p,
            rx_errors=rx_e,
            rx_drops=rx_d,
            tx_bytes=tx_b,
            tx_packets=tx_p,
            tx_errors=tx_e,
            tx_drops=tx_d,
        )
