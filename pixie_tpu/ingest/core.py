"""IngestCore — the Stirling-equivalent runtime.

Ref: src/stirling/stirling.{h,cc} — Stirling (stirling.h:91): registry of
SourceConnectors, RegisterDataPushCallback (:109), GetPublishProto/schema
publish (core/pub_sub_manager.*), RunAsThread (:163), and the RunCore poll
loop (stirling.cc:802-852): per source, if sampling expired TransferData;
if push expired PushData; sleep until the next tick.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

from pixie_tpu.ingest.source_connector import SourceConnector
from pixie_tpu.types import Relation

# push_cb(table_name: str, tablet: str, columns: dict) -> None
DataPushCallback = Callable[[str, str, dict], None]


class IngestCore:
    def __init__(self):
        from pixie_tpu.ingest.stirling_error import StirlingErrorConnector

        self._sources: list[SourceConnector] = []
        self._push_cb: Optional[DataPushCallback] = None
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._ctx = None
        # Self-monitoring (ref: stirling_error source connector):
        # connector init results and transfer errors become queryable
        # `stirling_error` rows instead of log lines.
        self.error_connector = StirlingErrorConnector()
        self._sources.append(self.error_connector)

    # -- registration (stirling.h:91-130) -----------------------------------
    def register_source(self, source: SourceConnector) -> None:
        self._sources.append(source)

    def deregister_source(self, source: SourceConnector) -> None:
        """Remove a source (dynamic tracepoint deletion). Safe while the
        run loop is live: the loop iterates over a snapshot."""
        try:
            self._sources.remove(source)
        except ValueError:
            pass

    def register_data_push_callback(self, cb: DataPushCallback) -> None:
        self._push_cb = cb

    def set_context(self, ctx) -> None:
        """Connector context (metadata state for PID→pod resolution;
        ref: InitContext / ConnectorContext)."""
        self._ctx = ctx

    def publish(self) -> dict[str, Relation]:
        """Table schemas this core produces (ref: GetPublishProto /
        InfoClassManager)."""
        out: dict[str, Relation] = {}
        for s in self._sources:
            for dt in s.tables:
                out[dt.name] = dt.relation
        return out

    def wire_to_table_store(self, store, device_executor=None) -> None:
        """Create the published tables in a TableStore and point the push
        callback at it — the PEM wiring (ref: pem_manager registers
        Stirling's DataPushCallback to TableStore::WriteHot). Tablet tables
        are created on first push (the reference creates tablets on
        demand).

        With ``device_executor`` given (and flag ``resident_ingest``),
        every wired table — including dynamically-created tablets —
        gets an HBM-resident ring (r13, serving/resident.py): the
        ingest loop's appends stage incrementally to the device, so a
        query over continuous telemetry finds its recent windows
        already resident and stages only the cold tail. A store whose
        engine wired its own create listener (engine.py) composes fine:
        ring enablement is idempotent per table."""
        from pixie_tpu.table.table import Table

        def enable_ring(t) -> None:
            if device_executor is not None and hasattr(
                device_executor, "enable_resident_ingest"
            ):
                device_executor.enable_resident_ingest(t)

        relations = self.publish()
        for name, rel in relations.items():
            t = store.get_table(name)
            if t is None:
                t = store.create_table(name, rel)
            enable_ring(t)

        def push(table_name: str, tablet: str, columns: dict) -> None:
            t = store.get_table(table_name, tablet or "")
            if t is None:
                rel = relations.get(table_name)
                if rel is None:
                    # Sources that build their DataTables in init_impl
                    # (e.g. SocketTraceConnector) publish nothing at
                    # wiring time — resolve live on first push.
                    rel = self.publish().get(table_name)
                    if rel is None:
                        raise KeyError(
                            f"no relation published for {table_name!r}"
                        )
                    relations[table_name] = rel
                t = Table(rel, name=table_name)
                store.add_table(table_name, t, tablet_id=tablet or "")
                enable_ring(t)
            t.write_pydict(columns)

        self.register_data_push_callback(push)

    # -- observability -------------------------------------------------------
    def status(self) -> dict:
        """Ingest-plane observability: per-source ``ingest_status()``
        snapshots (the r24 accounting/ladder/quarantine state) keyed by
        source name — surfaced by agent heartbeats and /statusz."""
        out: dict[str, dict] = {}
        for s in list(self._sources):
            fn = getattr(s, "ingest_status", None)
            if fn is None:
                continue
            try:
                out[s.name] = fn()
            except Exception:
                continue
        return out

    # -- run loop (stirling.cc:802-852) -------------------------------------
    def run(self) -> None:
        assert self._push_cb is not None, "no data push callback registered"
        for s in list(self._sources):
            s.error_recorder = self.error_connector.record
            try:
                s.init()
                if s is not self.error_connector:
                    self.error_connector.record(
                        s.name, 0, context={"event": "init"}
                    )
            except Exception as e:
                # Record ONCE and drop the source: a connector that never
                # initialized cannot transfer or push.
                self.error_connector.record(
                    s.name, 2, error=str(e), context={"event": "init"}
                )
                self.deregister_source(s)
        try:
            while not self._stop.is_set():
                now = time.monotonic()
                for s in list(self._sources):
                    if s.sampling_expired(now):
                        try:
                            s.transfer_data(self._ctx)
                        except Exception as e:
                            # One failing connector must not kill the
                            # whole ingest loop; the failure is queryable
                            # (ref: stirling_error posture).
                            self.error_connector.record(
                                s.name,
                                2,
                                error=str(e),
                                context={"event": "transfer_data"},
                            )
                        s.reset_sample(now)
                    if s.push_expired(now):
                        try:
                            s.push_data(self._push_cb)
                        except Exception as e:
                            self.error_connector.record(
                                s.name,
                                2,
                                error=str(e),
                                context={"event": "push_data"},
                            )
                        s.reset_push(now)
                next_tick = min(
                    (s.next_tick() for s in list(self._sources)),
                    default=now + 0.1,
                )
                self._stop.wait(timeout=max(0.0, next_tick - time.monotonic()))
        finally:
            # Final flush so short-lived runs lose nothing. Wrapped
            # per-source: one failing source must not skip the flush and
            # stop of every remaining source (and the error connector
            # flushes LAST so failures recorded here still land).
            sources = list(self._sources)
            if self.error_connector in sources:
                sources.remove(self.error_connector)
                sources.append(self.error_connector)
            for s in sources:
                try:
                    s.push_data(self._push_cb)
                except Exception as e:
                    self.error_connector.record(
                        s.name,
                        2,
                        error=str(e),
                        context={"event": "final_flush"},
                    )
                try:
                    s.stop()
                except Exception as e:
                    self.error_connector.record(
                        s.name, 2, error=str(e), context={"event": "stop"}
                    )

    def run_as_thread(self) -> None:
        """ref: Stirling::RunAsThread (stirling.h:163)."""
        self._thread = threading.Thread(target=self.run, daemon=True)
        self._thread.start()

    def stop(self, timeout: float = 2.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            self._thread = None
