"""Synthetic HTTP/conn telemetry generator.

The load-generation analogue of the socket tracer's output tables
(ref: src/stirling/source_connectors/socket_tracer/http_table.h:41,
conn_stats_table.h:29): emits `http_events` and `conn_stats` rows with the
reference's full column shapes, at a configurable rate. This is BASELINE
config 5's data source and the stand-in for eBPF collection on TPU hosts.

conn_stats semantics match the reference's: per-(upid, remote) rows carry
MONOTONIC counters (bytes_sent/recv, conn_open/close) sampled periodically,
so consumers take max-min deltas (px/net_flow_graph does exactly that).
"""

from __future__ import annotations

import time

import numpy as np

from pixie_tpu.ingest.source_connector import DataTable, SourceConnector
from pixie_tpu.types import DataType, Relation, SemanticType

I, F, S, T, B = (
    DataType.INT64,
    DataType.FLOAT64,
    DataType.STRING,
    DataType.TIME64NS,
    DataType.BOOLEAN,
)

# ref: http_table.h kHTTPElements (full column set)
HTTP_EVENTS_REL = Relation.of(
    ("time_", T, SemanticType.ST_TIME_NS),
    ("upid", S, SemanticType.ST_UPID),
    ("remote_addr", S, SemanticType.ST_IP_ADDRESS),
    ("remote_port", I),
    ("trace_role", I),
    ("major_version", I),
    ("minor_version", I),
    ("content_type", I),
    ("req_headers", S),
    ("req_method", S, SemanticType.ST_HTTP_REQ_METHOD),
    ("req_path", S),
    ("req_body", S),
    ("req_body_size", I, SemanticType.ST_BYTES),
    ("resp_headers", S),
    ("resp_status", I, SemanticType.ST_HTTP_RESP_STATUS),
    ("resp_message", S, SemanticType.ST_HTTP_RESP_MESSAGE),
    ("resp_body", S),
    ("resp_body_size", I, SemanticType.ST_BYTES),
    ("latency", I, SemanticType.ST_DURATION_NS),
)

# ref: conn_stats_table.h kConnStatsElements
CONN_STATS_REL = Relation.of(
    ("time_", T, SemanticType.ST_TIME_NS),
    ("upid", S, SemanticType.ST_UPID),
    ("remote_addr", S, SemanticType.ST_IP_ADDRESS),
    ("remote_port", I),
    ("trace_role", I),
    ("addr_family", I),
    ("protocol", I),
    ("ssl", B),
    ("conn_open", I),
    ("conn_close", I),
    ("conn_active", I),
    ("bytes_sent", I, SemanticType.ST_BYTES),
    ("bytes_recv", I, SemanticType.ST_BYTES),
)

METHODS = np.array(["GET", "GET", "GET", "POST", "PUT", "DELETE"], dtype=object)
MESSAGES = {200: "OK", 301: "Moved Permanently", 404: "Not Found",
            500: "Internal Server Error"}


class HTTPEventsConnector(SourceConnector):
    name = "http_gen"
    sample_period_s = 0.02
    push_period_s = 0.1

    def __init__(
        self,
        rows_per_sample: int = 1000,
        n_services: int = 8,
        n_paths: int = 32,
        seed: int = 0,
    ):
        super().__init__()
        self.rows_per_sample = rows_per_sample
        self.rng = np.random.default_rng(seed)
        self.upids = np.array(
            [f"1:{i}:{i * 7 + 1}" for i in range(n_services)], dtype=object
        )
        self.addrs = np.array(
            [f"10.0.{i // 256}.{i % 256}" for i in range(n_services)],
            dtype=object,
        )
        self.paths = np.array(
            [f"/api/v1/ep{i}" for i in range(n_paths)], dtype=object
        )
        # Monotonic per-(upid, remote) counters for conn_stats: one logical
        # connection pair per (service i -> addr of service (i+1) % n) edge.
        n_pairs = n_services
        self._pair_upid = self.upids
        self._pair_addr = self.addrs[(np.arange(n_pairs) + 1) % n_services]
        self._bytes_sent = np.zeros(n_pairs, np.int64)
        self._bytes_recv = np.zeros(n_pairs, np.int64)
        self._conn_open = np.zeros(n_pairs, np.int64)
        self._conn_close = np.zeros(n_pairs, np.int64)
        self.tables = [
            DataTable("http_events", HTTP_EVENTS_REL),
            DataTable("conn_stats", CONN_STATS_REL),
        ]

    def transfer_data_impl(self, ctx) -> None:
        n = self.rows_per_sample
        rng = self.rng
        now = time.time_ns()
        svc = rng.integers(0, len(self.upids), n)
        status = rng.choice([200, 200, 200, 200, 301, 404, 500], n)
        self.tables[0].append_columns(
            {
                "time_": now + np.arange(n),
                "upid": self.upids[svc],
                "remote_addr": self.addrs[rng.integers(0, len(self.addrs), n)],
                "remote_port": rng.integers(1024, 65535, n),
                "trace_role": rng.choice([1, 2], n, p=[0.2, 0.8]),
                "major_version": rng.choice([1, 2], n, p=[0.8, 0.2]),
                "minor_version": np.ones(n, np.int64),
                "content_type": rng.integers(0, 3, n),
                "req_headers": np.full(n, '{"Host":"svc"}', dtype=object),
                "req_method": METHODS[rng.integers(0, len(METHODS), n)],
                "req_path": self.paths[rng.integers(0, len(self.paths), n)],
                "req_body": np.full(n, "", dtype=object),
                "req_body_size": rng.integers(32, 1 << 10, n),
                "resp_headers": np.full(
                    n, '{"Content-Type":"application/json"}', dtype=object
                ),
                "resp_status": status,
                "resp_message": np.array(
                    [MESSAGES.get(s, "") for s in status], dtype=object
                ),
                "resp_body": np.full(n, "{}", dtype=object),
                "resp_body_size": rng.integers(64, 1 << 16, n),
                "latency": rng.integers(10**5, 10**9, n),
            }
        )
        # conn_stats: advance every pair's counters, emit one sample per
        # pair per tick (client side, trace_role=1).
        m = len(self._pair_upid)
        self._bytes_sent += rng.integers(1 << 8, 1 << 16, m)
        self._bytes_recv += rng.integers(1 << 8, 1 << 16, m)
        self._conn_open += rng.integers(0, 3, m)
        self._conn_close += np.minimum(
            rng.integers(0, 2, m), self._conn_open - self._conn_close
        )
        self.tables[1].append_columns(
            {
                "time_": now + np.arange(m),
                "upid": self._pair_upid,
                "remote_addr": self._pair_addr,
                "remote_port": np.full(m, 8080, np.int64),
                "trace_role": np.ones(m, np.int64),
                "addr_family": np.full(m, 2, np.int64),  # AF_INET
                "protocol": rng.integers(0, 5, m),
                "ssl": rng.integers(0, 2, m).astype(bool),
                "conn_open": self._conn_open.copy(),
                "conn_close": self._conn_close.copy(),
                "conn_active": self._conn_open - self._conn_close,
                "bytes_sent": self._bytes_sent.copy(),
                "bytes_recv": self._bytes_recv.copy(),
            }
        )
