"""Synthetic HTTP/conn telemetry generator.

The load-generation analogue of the socket tracer's output tables
(ref: src/stirling/source_connectors/socket_tracer/http_table.h,
conn_stats_table.h): emits `http_events` and `conn_stats` rows with the
reference's column shapes, at a configurable rate. This is BASELINE
config 5's data source and the stand-in for eBPF collection on TPU hosts.
"""

from __future__ import annotations

import time

import numpy as np

from pixie_tpu.ingest.source_connector import DataTable, SourceConnector
from pixie_tpu.types import DataType, Relation, SemanticType

I, F, S, T = (
    DataType.INT64,
    DataType.FLOAT64,
    DataType.STRING,
    DataType.TIME64NS,
)

# ref: http_table.h column set (trimmed to the queried columns)
HTTP_EVENTS_REL = Relation.of(
    ("time_", T, SemanticType.ST_TIME_NS),
    ("upid", S, SemanticType.ST_UPID),
    ("remote_addr", S, SemanticType.ST_IP_ADDRESS),
    ("remote_port", I),
    ("req_method", S),
    ("req_path", S),
    ("resp_status", I),
    ("resp_body_size", I, SemanticType.ST_BYTES),
    ("latency", I, SemanticType.ST_DURATION_NS),
)

# ref: conn_stats_table.h
CONN_STATS_REL = Relation.of(
    ("time_", T, SemanticType.ST_TIME_NS),
    ("upid", S, SemanticType.ST_UPID),
    ("remote_addr", S, SemanticType.ST_IP_ADDRESS),
    ("remote_port", I),
    ("protocol", I),
    ("bytes_sent", I, SemanticType.ST_BYTES),
    ("bytes_recv", I, SemanticType.ST_BYTES),
)

METHODS = np.array(["GET", "GET", "GET", "POST", "PUT", "DELETE"], dtype=object)


class HTTPEventsConnector(SourceConnector):
    name = "http_gen"
    sample_period_s = 0.02
    push_period_s = 0.1

    def __init__(
        self,
        rows_per_sample: int = 1000,
        n_services: int = 8,
        n_paths: int = 32,
        seed: int = 0,
    ):
        super().__init__()
        self.rows_per_sample = rows_per_sample
        self.rng = np.random.default_rng(seed)
        self.upids = np.array(
            [f"1:{i}:{i * 7 + 1}" for i in range(n_services)], dtype=object
        )
        self.addrs = np.array(
            [f"10.0.{i // 256}.{i % 256}" for i in range(n_services)],
            dtype=object,
        )
        self.paths = np.array(
            [f"/api/v1/ep{i}" for i in range(n_paths)], dtype=object
        )
        self.tables = [
            DataTable("http_events", HTTP_EVENTS_REL),
            DataTable("conn_stats", CONN_STATS_REL),
        ]

    def transfer_data_impl(self, ctx) -> None:
        n = self.rows_per_sample
        rng = self.rng
        now = time.time_ns()
        svc = rng.integers(0, len(self.upids), n)
        self.tables[0].append_columns(
            {
                "time_": now + np.arange(n),
                "upid": self.upids[svc],
                "remote_addr": self.addrs[rng.integers(0, len(self.addrs), n)],
                "remote_port": rng.integers(1024, 65535, n),
                "req_method": METHODS[rng.integers(0, len(METHODS), n)],
                "req_path": self.paths[rng.integers(0, len(self.paths), n)],
                "resp_status": rng.choice(
                    [200, 200, 200, 200, 301, 404, 500], n
                ),
                "resp_body_size": rng.integers(64, 1 << 16, n),
                "latency": rng.integers(10**5, 10**9, n),
            }
        )
        m = max(n // 10, 1)
        conn_svc = rng.integers(0, len(self.upids), m)
        self.tables[1].append_columns(
            {
                "time_": now + np.arange(m),
                "upid": self.upids[conn_svc],
                "remote_addr": self.addrs[rng.integers(0, len(self.addrs), m)],
                "remote_port": rng.integers(1024, 65535, m),
                "protocol": rng.integers(0, 5, m),
                "bytes_sent": rng.integers(0, 1 << 20, m),
                "bytes_recv": rng.integers(0, 1 << 20, m),
            }
        )
