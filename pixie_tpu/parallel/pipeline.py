"""The compiled device pipeline: source→map/filter→aggregate in ONE XLA
program over the mesh.

This is the TPU offload named in BASELINE.json: the exec-graph (host) path
stays the control/fallback engine, while fragments matching the hot shape

    MemorySource → (Map | Filter)* → Agg(FULL, not windowed)

compile into a single jit(shard_map(...)): each device lax.scans its shard
of staged blocks, evaluating the fused projection/predicate expressions and
updating UDA states via masked segment reductions; then one collective per
UDA merges states over ICI (lax.psum/pmax/pmin for elementwise MergeKinds,
all_gather + tree fold for TREE sketches like t-digest). Host work is
limited to dictionary LUTs, gid densification for non-string keys, staging,
and finalize.

Ref mapping: per-device scan ≙ the PEM pre-blocking fragment
(splitter.h:52); the collective ≙ Kelvin's cross-PEM merge
(partial_op_mgr.h:94 + the gRPC data plane it rides in the reference).
"""

from __future__ import annotations

import collections
import dataclasses
import functools
import re
import threading
import time
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # jax>=0.4.35 exposes shard_map at top level
    from jax import shard_map  # type: ignore[attr-defined]
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map

# Replication checking kwarg was renamed check_rep -> check_vma across jax
# versions; probe the actual signature once.
import inspect as _inspect

_SM_PARAMS = _inspect.signature(shard_map).parameters
if "check_vma" in _SM_PARAMS:
    _SM_CHECK_KW = {"check_vma": False}
elif "check_rep" in _SM_PARAMS:  # pragma: no cover - older jax
    _SM_CHECK_KW = {"check_rep": False}
else:  # pragma: no cover
    _SM_CHECK_KW = {}

from pixie_tpu.compiler.analyzer import substitute
from pixie_tpu.exec.expression_evaluator import ExpressionEvaluator
from pixie_tpu.exec.group_encoder import GroupEncoder
from pixie_tpu.parallel.staging import (
    DEFAULT_BLOCK_ROWS,
    _pow2_at_least,
    read_columns,
    stage_columns,
)
from pixie_tpu.plan.expressions import (
    AggregateExpression,
    ColumnRef,
    Constant,
    FuncCall,
    expr_data_type,
    referenced_columns,
    walk,
)
from pixie_tpu.plan.operators import (
    AggOp,
    AggStage,
    FilterOp,
    JoinOp,
    JoinType,
    LimitOp,
    MapOp,
    MemorySourceOp,
)
from pixie_tpu.plan.plan import PlanFragment
from pixie_tpu.table.column import DictColumn, StringDictionary
from pixie_tpu.table.row_batch import RowBatch
from pixie_tpu.types import DataType
from pixie_tpu.types.dtypes import host_dtype
from pixie_tpu.udf.udf import Executor, MergeKind
from pixie_tpu.parallel import profiler as resattr
from pixie_tpu.distributed import mesh as mesh_lib
from pixie_tpu.utils import faults, flags, metrics_registry, trace

# r22 learned cost model, resolved lazily (serving's package init
# transitively imports this module, so a top-level import would cycle).
# After first resolution every gate is `_cost_model().ACTIVE` — a cached
# global + attribute load, held <1% by microbench_fault_overhead's
# cost_model_overhead key.
_COST_MODEL = None


def _cost_model():
    global _COST_MODEL
    if _COST_MODEL is None:
        from pixie_tpu.serving import cost_model

        _COST_MODEL = cost_model
    return _COST_MODEL

_M = metrics_registry()
_OFFLOAD_HITS = _M.counter(
    "device_offload_total", "Fragments executed on the device mesh."
)
_OFFLOAD_MISS = _M.counter(
    "device_offload_unmatched_total",
    "Fragments that did not match the device-offloadable shape.",
)
_OFFLOAD_FALLBACKS = _M.counter(
    "device_offload_fallback_total",
    "Device offload attempts that failed and fell back to the host engine.",
)
_BREAKER_TRIPS = _M.counter(
    "device_offload_fallback_breaker_trips_total",
    "Circuit-breaker trips: N consecutive device failures sent a program "
    "key to the host engine for a cooldown.",
)
_BREAKER_SKIPS = _M.counter(
    "device_offload_fallback_breaker_open_total",
    "Fragments routed straight to the host engine because their program "
    "key's circuit breaker was open.",
)
_PROGRAMS = _M.gauge(
    "device_program_cache_size", "Compiled shard_map programs cached."
)
_MESH_DEGRADE = _M.counter(
    "mesh_degrade_events_total",
    "Mesh geometry failures (host loss / hung collective) recovered by "
    "re-planning the fold onto the next degradation rung (r23; the "
    "retried answer is bit-identical by the r21 geometry invariant).",
)
_MESH_CKPT_WINDOWS = _M.counter(
    "mesh_checkpoint_windows_total",
    "Stream-fold windows whose carried UDA state was checkpointed "
    "host-side at the window boundary (flag mesh_fold_checkpoint).",
)
_MESH_RESUMES = _M.counter(
    "mesh_checkpoint_resumes_total",
    "Stream folds resumed from a window checkpoint on a surviving "
    "geometry instead of refolding from scratch.",
)

# One multi-axis collective program in flight per process: two
# concurrent all-device programs interleave their per-device executions
# in different orders and deadlock the rendezvous (observed on the
# 8-virtual-device CPU sim the moment two executors folded at
# hosts:2,d:4 at once). Flat single-axis dispatches carry no cross-host
# rendezvous and never take this lock.
_MESH_COLLECTIVE_LOCK = threading.Lock()

# Persistent-compilation-cache hit counter: jax emits a monitoring event
# per .jax_cache deserialization; the AOT compile thread snapshots it
# around each compile so the ledger's compile_cache_hit key is honest
# (a hit = the bucketed signature reproduced a prior round's HLO).
_PERSISTENT_CACHE_HITS = [0]


def _on_jax_monitoring_event(event, *args, **kwargs):
    if event == "/jax/compilation_cache/cache_hits":
        _PERSISTENT_CACHE_HITS[0] += 1


try:
    jax.monitoring.register_event_listener(_on_jax_monitoring_event)
except Exception:  # pragma: no cover - monitoring API drift
    pass

# Cold-path phase timings live in staging (shared with the transfer
# layer); re-exported here for callers.
from pixie_tpu.parallel.staging import (  # noqa: E402
    COLD_PROFILE,
    reset_cold_profile,
    timed as _timed,
)


@dataclasses.dataclass
class _Match:
    source_nid: int
    agg_nid: int
    source_op: MemorySourceOp
    agg_op: AggOp
    col_exprs: dict[str, Any]   # pre-agg column name -> expr in source terms
    predicates: list            # filter exprs in source terms
    source_relation: Any


def match_fragment(fragment: PlanFragment, relations) -> Optional[_Match]:
    """Find the source→(map|filter)*→agg chain, composing expressions into
    source-column terms along the way."""
    agg_nid = None
    for nid in fragment.topo_order():
        op = fragment.node(nid)
        # FULL aggs finalize on device (windowed ones too, r5: the window
        # id becomes a second group axis and each window emits its own
        # batch); PARTIAL aggs (the PEM side of a distributed split) ship
        # raw states to the merge stage — windowed PARTIALs stay on the
        # host, whose eow-driven StateBatch cadence the merge consumes.
        if isinstance(op, AggOp) and (
            op.stage == AggStage.FULL
            or (op.stage == AggStage.PARTIAL and not op.windowed)
        ):
            agg_nid = nid
            break
    if agg_nid is None:
        return None
    # Walk up to the source.
    chain = []
    cur = agg_nid
    while True:
        parents = fragment.parents(cur)
        if len(parents) != 1:
            return None
        cur = parents[0]
        op = fragment.node(cur)
        if len(fragment.children(cur)) != 1:
            return None  # shared with another branch: host engine's job
        if isinstance(op, MemorySourceOp):
            if op.streaming:
                return None  # streaming stays with the live host cursor
            source_nid = cur
            break
        if not isinstance(op, (MapOp, FilterOp)):
            return None
        chain.append(op)
    chain.reverse()
    source_rel = relations[source_nid]
    mapping = {c.name: ColumnRef(c.name) for c in source_rel}
    preds = []
    for op in chain:
        if isinstance(op, FilterOp):
            preds.append(substitute(op.expr, mapping))
        else:
            mapping = {
                name: substitute(e, mapping) for name, e in op.exprs
            }
    return _Match(
        source_nid=source_nid,
        agg_nid=agg_nid,
        source_op=fragment.node(source_nid),
        agg_op=fragment.node(agg_nid),
        col_exprs=mapping,
        predicates=preds,
        source_relation=source_rel,
    )


# -- predicate normalization (r16; module-level since r20) -------------------
# Lowers conjunctive predicate trees to data terms
# ``(stack, column, op, int_thr, flt_thr, in_vals)``. One normalizer,
# three consumers with the identical refusal class: the predicate-batched
# shared scans (MeshExecutor), the r20 join-side pushdown, and the
# materialized-view predicate digest (serving/views.py).

_CMP_OPS = {
    "equal": 0, "notEqual": 1,
    "lessThan": 2, "lessThanEqual": 3,
    "greaterThan": 4, "greaterThanEqual": 5,
}
# const-on-the-left flips the comparison, not the operands.
_CMP_FLIP = {0: 0, 1: 1, 2: 4, 3: 5, 4: 2, 5: 3}


def normalize_predicates(predicates, evaluator, staged, aux):
    """Lower ``predicates`` to conjunctive data terms
    ``(stack, column, op, int_thr, flt_thr, in_vals)`` — or None
    when any predicate falls outside the normalizable class (the
    query then only shares via the identical-signature ladder).

    The class is a direct comparison of a staged column against a
    constant (either order), a bare boolean column, a conjunction
    (logical_and splits into more terms), and — r18 — an IN-list:
    a logical_or tree whose leaves are all ``equal(same_col,
    const)`` folds into ONE membership term (op 6) whose values
    ride a per-term LUT lane in the batched fold, so IN-heavy
    query families join predicate batches instead of falling back
    to solo folds; and — r22 — a LUT-backed host-func predicate
    (``f(col)`` or ``cmp(f(col), c)`` over a dictionary column,
    via ``_lut_pred_term``), which collapses to the op-6
    membership of the codes the precomputed per-value table
    keeps. Exactness contract per term: int/bool/code
    columns compare in int64 (every staged int value and
    dictionary code fits exactly); float columns compare in
    float64 with the threshold pre-rounded through the column's
    STAGED dtype (an f32-staged column's serial comparison happens
    in f32 — float64(f32(c)) preserves both its equalities and its
    ordering, so the batched mask is bit-equal). Float IN-lists
    are refused (the serial OR-of-equals is exact, but folding it
    through one LUT dtype is not worth proving). String constants
    ride as their dictionary code from the aux table (-1 for
    unseen: equal to nothing, exactly the serial code-compare
    semantics — including inside an IN LUT, where -1 matches no
    row code); columns re-encoded for the cell lane (int_dicts)
    hold codes the serial path would ALSO compare raw, so they are
    refused rather than guessed at."""
    terms = []
    for p in predicates:
        if not _normalize_pred(p, evaluator, staged, aux, terms):
            return None
    return terms


def _normalize_pred(p, evaluator, staged, aux, terms):
    """Normalize one predicate tree into ``terms``. True on
    success; False means the whole attempt is refused."""
    if isinstance(p, ColumnRef):
        if (
            p.name not in staged.blocks
            or p.name in staged.int_dicts
            or np.dtype(staged.blocks[p.name].dtype) != np.bool_
        ):
            return False
        terms.append(("i", p.name, 1, 0, 0.0, ()))  # col != 0
        return True
    if not isinstance(p, FuncCall):
        return False
    if p.name == "logical_and" and len(p.args) == 2:
        # A conjunction is just more terms.
        return _normalize_pred(
            p.args[0], evaluator, staged, aux, terms
        ) and _normalize_pred(
            p.args[1], evaluator, staged, aux, terms
        )
    if p.name == "logical_or" and len(p.args) == 2:
        t = _in_list_term(p, evaluator, staged, aux)
        if t is None:
            return False
        terms.append(t)
        return True
    t = _lut_pred_term(p, evaluator, staged, aux)
    if t is not None:
        terms.append(t)
        return True
    if len(p.args) != 2:
        return False
    op = _CMP_OPS.get(p.name)
    if op is None:
        return False
    a0, a1 = p.args
    if isinstance(a0, ColumnRef) and isinstance(a1, Constant):
        col, const = a0, a1
    elif isinstance(a1, ColumnRef) and isinstance(a0, Constant):
        col, const = a1, a0
        op = _CMP_FLIP[op]
    else:
        return False
    if col.name not in staged.blocks or (
        col.name in staged.int_dicts
    ):
        return False
    resolved = evaluator._resolved.get(id(p))
    if resolved is None:
        return False
    _udf, arg_types = resolved
    t0 = arg_types[0]
    bdt = np.dtype(staged.blocks[col.name].dtype)
    if t0 == DataType.STRING:
        if op > 1:
            return False  # only ==/!= have code-space semantics
        code = aux.get(f"const:{id(const)}")
        if code is None:
            return False
        terms.append(("i", col.name, op, int(code), 0.0, ()))
    elif t0 == DataType.FLOAT64:
        v = const.value
        if not isinstance(
            v, (int, float, np.floating, np.integer)
        ) or isinstance(v, bool):
            return False
        if bdt == np.float32:
            thr = float(np.float64(np.float32(v)))
        elif bdt == np.float64:
            thr = float(v)
        else:
            return False
        terms.append(("f", col.name, op, 0, thr, ()))
    elif t0 in (
        DataType.INT64, DataType.TIME64NS, DataType.BOOLEAN,
    ):
        if bdt.kind == "f":
            return False
        try:
            thr = int(const.value)
        except (TypeError, ValueError):
            return False
        if not (-(1 << 63) <= thr < (1 << 63)):
            return False
        terms.append(("i", col.name, op, thr, 0.0, ()))
    else:
        return False
    return True


def _in_list_term(p, evaluator, staged, aux):
    """Fold a ``logical_or`` tree whose leaves are all
    ``equal(same_col, const)`` into one membership term
    ``("i", col, 6, 0, 0.0, codes)`` — the compiler lowers
    ``col in [a, b, ...]`` to exactly this shape. None refuses."""
    leaves = []
    stack = [p]
    while stack:
        n = stack.pop()
        if (
            isinstance(n, FuncCall)
            and n.name == "logical_or"
            and len(n.args) == 2
        ):
            stack.extend(n.args)
        else:
            leaves.append(n)
    col_name = None
    vals = []
    for leaf in leaves:
        if (
            not isinstance(leaf, FuncCall)
            or leaf.name != "equal"
            or len(leaf.args) != 2
        ):
            return None
        a0, a1 = leaf.args
        if isinstance(a0, ColumnRef) and isinstance(a1, Constant):
            col, const = a0, a1
        elif isinstance(a1, ColumnRef) and isinstance(a0, Constant):
            col, const = a1, a0
        else:
            return None
        if col_name is None:
            col_name = col.name
        elif col.name != col_name:
            return None
        if col.name not in staged.blocks or (
            col.name in staged.int_dicts
        ):
            return None
        resolved = evaluator._resolved.get(id(leaf))
        if resolved is None:
            return None
        _udf, arg_types = resolved
        t0 = arg_types[0]
        if t0 == DataType.STRING:
            code = aux.get(f"const:{id(const)}")
            if code is None:
                return None
            vals.append(int(code))
        elif t0 in (
            DataType.INT64, DataType.TIME64NS, DataType.BOOLEAN,
        ):
            if np.dtype(staged.blocks[col.name].dtype).kind == "f":
                return None
            try:
                v = int(const.value)
            except (TypeError, ValueError):
                return None
            if not (-(1 << 63) <= v < (1 << 63)):
                return None
            vals.append(v)
        else:
            return None  # float IN-lists are refused
    if col_name is None or not vals:
        return None
    # Membership is order/multiplicity-insensitive; sort+dedup so
    # equivalent IN-lists share one slot under the exact-key ladder.
    return ("i", col_name, 6, 0, 0.0, tuple(sorted(set(vals))))


# numpy mirrors of the device comparison ids — x64 is enabled globally
# (pixie_tpu/__init__), so host-numpy and on-device jnp comparisons of
# the same LUT values against the same scalar agree bitwise.
_NP_CMP = {
    0: np.equal, 1: np.not_equal, 2: np.less,
    3: np.less_equal, 4: np.greater, 5: np.greater_equal,
}
# Bound on the op-6 lane width a LUT predicate may demand: a predicate
# keeping more dictionary values than this refuses normalization (the
# query still folds solo) rather than inflating the batched fold's L
# bucket for every co-batched query.
_LUT_PRED_MAX_KEPT = 1024


def _lut_pred_term(p, evaluator, staged, aux):
    """r22 (r18 carry-over): lower a LUT-backed host-func predicate to
    one membership term. Two shapes: a bare boolean host func over one
    dictionary column (``f(col)`` whose aux table ``lut:{id(p)}`` was
    precomputed by ``build_aux``) and a comparison of such a func
    against a numeric constant (``cmp(f(col), c)``, either order).
    Both reduce to the SET OF DICTIONARY CODES the predicate keeps —
    an op-6 membership term over the column's code block. This is
    bit-equal to the solo device path by construction: the solo fold
    gathers the SAME per-code table and masks on (a comparison of) the
    gathered value, so row code ``k`` survives there iff ``lut[k]``
    passes — exactly membership of ``k`` in the kept set (an empty
    kept set keeps nothing on both paths). None refuses: no LUT in
    ``aux`` (host/digest shim, or not dict_compatible), a non-bool LUT
    on the bare shape, string/bool constants, or a kept set wider than
    the op-6 lane cap."""
    op = _CMP_OPS.get(p.name)
    const = None
    if op is not None and len(p.args) == 2:
        a0, a1 = p.args
        if isinstance(a0, FuncCall) and isinstance(a1, Constant):
            f_expr, const = a0, a1
        elif isinstance(a1, FuncCall) and isinstance(a0, Constant):
            f_expr, const = a1, a0
            op = _CMP_FLIP[op]
        else:
            return None
    elif f"lut:{id(p)}" in aux:
        f_expr, op = p, None  # bare boolean func: keep where truthy
    else:
        return None
    lut = aux.get(f"lut:{id(f_expr)}")
    if lut is None:
        return None
    cols = [a for a in f_expr.args if isinstance(a, ColumnRef)]
    if len(cols) != 1:
        return None
    col = cols[0]
    if col.name not in staged.blocks or col.name in staged.int_dicts:
        return None
    lut = np.asarray(lut)
    if lut.ndim != 1 or lut.dtype.kind not in "bif":
        return None
    if op is None:
        # Bare predicate: the solo path ANDs the gathered value into a
        # boolean mask, which only traces for bool LUTs — mirror that.
        if lut.dtype != np.bool_:
            return None
        kept = lut
    else:
        v = const.value
        if not isinstance(
            v, (int, float, np.integer, np.floating)
        ) or isinstance(v, bool):
            return None
        kept = _NP_CMP[op](lut, v)
    codes = np.nonzero(np.asarray(kept, dtype=bool))[0]
    if len(codes) > _LUT_PRED_MAX_KEPT:
        return None
    return ("i", col.name, 6, 0, 0.0, tuple(int(c) for c in codes))


@dataclasses.dataclass
class _HostNormShim:
    """Duck-typed StagedColumns stand-in for normalizing predicates
    WITHOUT a device staging (r20): ``blocks`` carries zero-length
    arrays in each column's HOST dtype (int32 for STRING code
    columns, ``host_dtype`` otherwise) so the normalizer's dtype
    gates resolve exactly as they would against a host-geometry
    staging; no cell-lane re-encoding ever applies."""

    blocks: dict
    int_dicts: dict = dataclasses.field(default_factory=dict)


def host_norm_shim(relation) -> _HostNormShim:
    blocks = {}
    for schema in relation:
        if schema.data_type == DataType.STRING:
            blocks[schema.name] = np.empty(0, dtype=np.int32)
        else:
            blocks[schema.name] = np.empty(
                0, dtype=host_dtype(schema.data_type)
            )
    return _HostNormShim(blocks)


def predicate_fold_digest(predicates, relation, registry, func_ctx=None):
    """Canonical digest of a conjunctive predicate list over
    ``relation``, or None when any predicate falls outside the
    normalizable class. Two suffixes with the same digest keep or
    drop exactly the same rows.

    String constants canonicalize BY VALUE, never by dictionary
    code: codes drift as dictionaries grow (and every unseen
    constant would collide on -1), so the normalizer runs over a
    private value-sorted code assignment whose codes are translated
    back to the string values in the emitted digest. Terms sort —
    a conjunction commutes — so predicate ORDER never splits a
    digest. Consumers: the r20 materialized-view match (a view
    serves a query only when the fold signature AND this digest
    agree) and the join-side pushdown's staging identity."""
    named = [(f"pred{i}", p) for i, p in enumerate(predicates)]
    try:
        evaluator = ExpressionEvaluator(
            named, relation, registry, func_ctx
        )
    except (ValueError, KeyError):
        return None
    svals = sorted(
        {
            e.value
            for _n, p in named
            for e in walk(p)
            if isinstance(e, Constant) and isinstance(e.value, str)
        }
    )
    code_of = {v: i for i, v in enumerate(svals)}
    aux = {}
    for _n, p in named:
        for e in walk(p):
            if isinstance(e, Constant) and isinstance(e.value, str):
                aux[f"const:{id(e)}"] = code_of[e.value]
    shim = host_norm_shim(relation)
    terms = normalize_predicates(predicates, evaluator, shim, aux)
    if terms is None:
        return None
    val_of_code = {c: v for v, c in code_of.items()}
    string_cols = {
        s.name for s in relation if s.data_type == DataType.STRING
    }
    canon = []
    for stack, col, op, ithr, fthr, invals in terms:
        if col in string_cols and op in (0, 1):
            canon.append((col, op, "s", val_of_code[ithr]))
        elif col in string_cols and op == 6:
            canon.append(
                (col, op, "s",
                 tuple(sorted(val_of_code[c] for c in invals)))
            )
        else:
            canon.append((col, op, stack, ithr, fthr, invals))
    return "preds:" + repr(sorted(canon, key=repr))


@dataclasses.dataclass
class _ScanMatch:
    """Source→(Map|Filter)*→Limit chain (no aggregate): the device
    evaluates predicates + projections and returns the first ``limit``
    surviving rows (ref: the reference's hot path includes plain
    filter/map scans, memory_source_node.h:42 → map/filter → limit;
    px/http_data always bounds output with head())."""

    source_nid: int
    limit_nid: int
    source_op: MemorySourceOp
    limit: int
    out_exprs: list  # [(name, expr in source terms)]
    predicates: list
    source_relation: Any
    out_relation: Any


def match_scan_fragment(fragment: PlanFragment, relations) -> Optional[_ScanMatch]:
    """Find MemorySource→(Map|Filter)*→Limit with single-parent/child
    links. Unbounded scans stay on the host: their output is the whole
    selection, and shipping it back row-for-row forfeits the offload."""
    for nid in fragment.topo_order():
        op = fragment.node(nid)
        if not isinstance(op, LimitOp):
            continue
        chain = []
        cur = nid
        source_nid = None
        while True:
            parents = fragment.parents(cur)
            if len(parents) != 1:
                return None
            cur = parents[0]
            pop = fragment.node(cur)
            if len(fragment.children(cur)) != 1:
                return None
            if isinstance(pop, MemorySourceOp):
                if pop.streaming:
                    return None
                source_nid = cur
                break
            if not isinstance(pop, (MapOp, FilterOp)):
                return None
            chain.append(pop)
        chain.reverse()
        source_rel = relations[source_nid]
        mapping = {c.name: ColumnRef(c.name) for c in source_rel}
        preds = []
        for pop in chain:
            if isinstance(pop, FilterOp):
                preds.append(substitute(pop.expr, mapping))
            else:
                mapping = {
                    name: substitute(e, mapping) for name, e in pop.exprs
                }
        out_rel = relations[nid]
        out_exprs = [(c.name, mapping[c.name]) for c in out_rel]
        return _ScanMatch(
            source_nid=source_nid,
            limit_nid=nid,
            source_op=fragment.node(source_nid),
            limit=op.n,
            out_exprs=out_exprs,
            predicates=preds,
            source_relation=source_rel,
            out_relation=out_rel,
        )
    return None


@dataclasses.dataclass
class _JoinAggMatch:
    """Source→(Map|Filter)*→⌐                                  ⌐→Agg
       Source→(Map|Filter)*→┘ INNER Join →(Map|Filter)* ┘

    Device join-aggregate decomposition: the join's PAIRS are never
    materialized. For decomposable aggregates, aggregating over the join
    equals aggregating the LEFT rows with per-row weight w = (number of
    matching RIGHT rows), plus per-key RIGHT statistics gathered by join
    key:  count ≡ Σ_L w;  sum(left x) ≡ Σ_L x·w;
    sum(right y) ≡ Σ_L sumR[y, key];  min/max(right y) ≡ min/max over
    L of minR/maxR[y, key].  The reference's EquijoinNode
    (equijoin_node.h:48) builds hash tables and materializes chunked
    output rows; on TPU the decomposition keeps everything in segment
    reductions over statically-shaped tensors."""

    left_source_nid: int
    right_source_nid: int
    join_nid: int
    agg_nid: int
    left_source_op: MemorySourceOp
    right_source_op: MemorySourceOp
    join_op: JoinOp
    agg_op: AggOp
    left_exprs: dict       # left source-term mapping (pre-join chain)
    right_exprs: dict      # right source-term mapping
    left_preds: list       # pre-join predicates, left source terms
    right_preds: list      # pre-join predicates, right source terms
    left_key_exprs: list   # join keys in left source terms
    right_key_exprs: list  # join keys in right source terms
    post_left_preds: list  # post-join predicates that touch only left side
    post_right_preds: list
    left_relation: Any
    right_relation: Any
    # agg specs rewritten: [(out_name, side, arg_expr_in_side_terms, agg_name)]
    specs: list
    group_exprs: list      # [(group_name, left-side expr)]


def _chain_to_source(fragment, start_nid, relations):
    """Walk (Map|Filter)* up to a non-streaming MemorySource; returns
    (source_nid, mapping, preds) or None."""
    chain = []
    cur = start_nid
    while True:
        op = fragment.node(cur)
        if isinstance(op, MemorySourceOp):
            if op.streaming:
                return None
            source_nid = cur
            break
        if not isinstance(op, (MapOp, FilterOp)):
            return None
        if len(fragment.children(cur)) != 1:
            return None
        chain.append(op)
        parents = fragment.parents(cur)
        if len(parents) != 1:
            return None
        cur = parents[0]
    chain.reverse()
    rel = relations[source_nid]
    mapping = {c.name: ColumnRef(c.name) for c in rel}
    preds = []
    for op in chain:
        if isinstance(op, FilterOp):
            preds.append(substitute(op.expr, mapping))
        else:
            mapping = {n: substitute(e, mapping) for n, e in op.exprs}
    return source_nid, mapping, preds, rel


def _expr_side(expr, left_cols: set, right_cols: set):
    """0 if the expression references only left-output columns, 1 if only
    right, None if mixed/unknown."""
    refs = referenced_columns(expr)
    if refs <= left_cols:
        return 0
    if refs <= right_cols:
        return 1
    return None


def match_join_agg(fragment: PlanFragment, relations) -> Optional[_JoinAggMatch]:
    join_nid = None
    for nid in fragment.topo_order():
        if isinstance(fragment.node(nid), JoinOp):
            join_nid = nid
            break
    if join_nid is None:
        return None
    join_op: JoinOp = fragment.node(join_nid)
    if join_op.how != JoinType.INNER or not join_op.left_on:
        return None
    parents = fragment.parents(join_nid)
    if len(parents) != 2 or len(fragment.children(join_nid)) != 1:
        return None
    left = _chain_to_source(fragment, parents[0], relations)
    right = _chain_to_source(fragment, parents[1], relations)
    if left is None or right is None:
        return None
    lsrc, lmap, lpreds, lrel = left
    rsrc, rmap, rpreds, rrel = right
    if lsrc == rsrc:
        return None  # self-join over one cursor: host engine's job
    # Walk DOWN from the join through (Map|Filter)* to the Agg.
    out_cols = {o: (side, name) for side, name, o in join_op.output_columns}
    post_map = {o: ColumnRef(o) for o in out_cols}
    post_preds = []
    cur = join_nid
    agg_nid = None
    while True:
        children = fragment.children(cur)
        if len(children) != 1:
            return None
        cur = children[0]
        op = fragment.node(cur)
        if isinstance(op, AggOp):
            # FULL only: a PARTIAL stage must emit serialized states for
            # its MERGE consumer, which this decomposition does not build.
            if op.windowed or op.stage != AggStage.FULL:
                return None
            if len(fragment.parents(cur)) != 1:
                return None
            agg_nid = cur
            break
        if isinstance(op, FilterOp):
            post_preds.append(substitute(op.expr, post_map))
        elif isinstance(op, MapOp):
            post_map = {n: substitute(e, post_map) for n, e in op.exprs}
        else:
            return None
    agg_op: AggOp = fragment.node(agg_nid)

    # Rewrite every post-join expression into single-side source terms.
    left_out = {o for o, (s, _) in out_cols.items() if s == 0}
    right_out = {o for o, (s, _) in out_cols.items() if s == 1}

    def rewrite(expr):
        side = _expr_side(expr, left_out, right_out)
        if side is None:
            return None
        src_map = lmap if side == 0 else rmap
        name_map = {
            o: substitute(ColumnRef(out_cols[o][1]), src_map)
            for o in (left_out if side == 0 else right_out)
        }
        return side, substitute(expr, name_map)

    post_left_preds, post_right_preds = [], []
    for p in post_preds:
        rw = rewrite(p)
        if rw is None:
            return None
        (post_left_preds if rw[0] == 0 else post_right_preds).append(rw[1])
    group_exprs = []
    for g in agg_op.groups:
        rw = rewrite(post_map[g] if g in post_map else ColumnRef(g))
        if rw is None or rw[0] != 0:
            return None  # v1: groups must come from the left side
        group_exprs.append((g, rw[1]))
    specs = []
    for out_name, agg in agg_op.values:
        if agg.name not in _JOIN_DECOMPOSABLE:
            return None
        if not agg.args:
            return None
        arg = substitute(agg.args[0], post_map)
        rw = rewrite(arg)
        if rw is None:
            return None
        specs.append((out_name, rw[0], rw[1], agg.name))
    # Join keys are named on each side's JOIN INPUT; map through the
    # pre-join chains into source terms.
    left_key_exprs = [substitute(ColumnRef(k), lmap) for k in join_op.left_on]
    right_key_exprs = [substitute(ColumnRef(k), rmap) for k in join_op.right_on]
    return _JoinAggMatch(
        left_source_nid=lsrc,
        right_source_nid=rsrc,
        join_nid=join_nid,
        agg_nid=agg_nid,
        left_source_op=fragment.node(lsrc),
        right_source_op=fragment.node(rsrc),
        join_op=join_op,
        agg_op=agg_op,
        left_exprs=lmap,
        right_exprs=rmap,
        left_preds=lpreds,
        right_preds=rpreds,
        left_key_exprs=left_key_exprs,
        right_key_exprs=right_key_exprs,
        post_left_preds=post_left_preds,
        post_right_preds=post_right_preds,
        left_relation=lrel,
        right_relation=rrel,
        specs=specs,
        group_exprs=group_exprs,
    )


# Aggregates with a join decomposition (count/sum/mean/min/max).
_JOIN_DECOMPOSABLE = {"count", "sum", "mean", "min", "max"}


@dataclasses.dataclass
class _JoinMatch:
    """Source→(Map|Filter)*→⌐
       Source→(Map|Filter)*→┘ Join(INNER/LEFT/RIGHT/OUTER) → [host suffix]

    Standalone-join decomposition (r19): unlike _JoinAggMatch the pairs
    ARE materialized — on device, by the sort-merge lane — and whatever
    follows the join runs on the host against the spliced batch."""

    left_source_nid: int
    right_source_nid: int
    join_nid: int
    left_source_op: MemorySourceOp
    right_source_op: MemorySourceOp
    join_op: JoinOp
    left_exprs: dict       # left source-term mapping (pre-join chain)
    right_exprs: dict
    left_preds: list       # pre-join predicates, left source terms
    right_preds: list
    left_key_exprs: list   # join keys in left source terms
    right_key_exprs: list
    left_relation: Any
    right_relation: Any
    out_relation: Any      # join output, in output_columns order


def match_join(fragment: PlanFragment, relations) -> Optional[_JoinMatch]:
    """Match a standalone equijoin whose inputs walk to two DISTINCT
    non-streaming sources. All four join types qualify; the suffix below
    the join (map/filter/agg/limit) stays host work on the spliced
    batch."""
    join_nid = None
    for nid in fragment.topo_order():
        if isinstance(fragment.node(nid), JoinOp):
            if join_nid is not None:
                return None  # multi-join plans: host engine's job
            join_nid = nid
    if join_nid is None:
        return None
    join_op: JoinOp = fragment.node(join_nid)
    if not join_op.left_on:
        return None
    parents = fragment.parents(join_nid)
    if len(parents) != 2:
        return None
    left = _chain_to_source(fragment, parents[0], relations)
    right = _chain_to_source(fragment, parents[1], relations)
    if left is None or right is None:
        return None
    lsrc, lmap, lpreds, lrel = left
    rsrc, rmap, rpreds, rrel = right
    if lsrc == rsrc:
        return None  # self-join over one cursor: host engine's job
    if join_op.how in (JoinType.RIGHT, JoinType.OUTER):
        # The host engine interleaves RIGHT/OUTER-unmatched probe rows
        # per probe batch; the device lane emits them after ALL matches.
        # Row order is not a join contract (preserves_time_order=False)
        # — except under a downstream Limit, which materializes the
        # first N rows of whatever order the engine produced. INNER and
        # LEFT device order is identical to the host's, so only the
        # outer-probe variants gate on Limit. (An upstream Limit already
        # fails _chain_to_source.)
        for nid in fragment.topo_order():
            if isinstance(fragment.node(nid), LimitOp):
                return None
    return _JoinMatch(
        left_source_nid=lsrc,
        right_source_nid=rsrc,
        join_nid=join_nid,
        left_source_op=fragment.node(lsrc),
        right_source_op=fragment.node(rsrc),
        join_op=join_op,
        left_exprs=lmap,
        right_exprs=rmap,
        left_preds=lpreds,
        right_preds=rpreds,
        left_key_exprs=[
            substitute(ColumnRef(k), lmap) for k in join_op.left_on
        ],
        right_key_exprs=[
            substitute(ColumnRef(k), rmap) for k in join_op.right_on
        ],
        left_relation=lrel,
        right_relation=rrel,
        out_relation=relations[join_nid],
    )


@dataclasses.dataclass
class _KeyPlan:
    """How group gids materialize. Exactly one of the modes applies:
    device_expr (codes/LUT gather on device) or host_gids (densified on
    host)."""

    device_expr: Optional[Any] = None
    host_gids: Optional[np.ndarray] = None
    num_groups: int = 0
    key_columns: list = dataclasses.field(default_factory=list)


class MeshExecutor:
    """Runs matching fragments on a jax device mesh (ref: the PEM fleet +
    Kelvin pair, collapsed into one SPMD program)."""

    def __init__(
        self,
        mesh: Optional[Mesh] = None,
        block_rows: Optional[int] = None,
        mesh_config: Optional["mesh_lib.MeshConfig"] = None,
    ):
        # Mesh geometry is declarative (distributed/mesh.py): an explicit
        # mesh wins, else mesh_config, else the mesh_axes flag (flat
        # single-host default). The geometry signature is embedded in
        # every compiled-program signature so a geometry change can
        # never silently reuse a stale executable.
        self.mesh, self.mesh_config = mesh_lib.resolve_mesh(mesh, mesh_config)
        mesh = self.mesh
        self.mesh_axes = mesh_lib.data_axes(mesh)
        self._mesh_sig = self.mesh_config.signature()
        # PIXIE_TPU_DEVICE_BLOCK_ROWS overrides; staging.DEFAULT_BLOCK_ROWS
        # is the built-in default.
        self.block_rows = (
            block_rows if block_rows is not None else flags.device_block_rows
        )
        # Compiled-program cache: structurally identical queries reuse the
        # traced+compiled shard_map (aux LUTs/constants are ARGUMENTS, so
        # dictionary growth does not invalidate the executable).
        self._program_cache: dict[str, Any] = {}
        # HBM-resident staged-table cache — the device-side cold tier: a
        # table version is staged once and every matching query hits HBM
        # directly (the reference's analogue is the compacted Arrow cold
        # store living next to the CPU; ours lives next to the MXU).
        # r12: a managed residency pool (serving/residency.py) — per-entry
        # byte accounting against hbm_budget_mb with high/low watermark
        # LRU eviction, query-scoped pinning (an in-flight fold's entry
        # is never evicted), and device_staged_bytes gauges; the
        # staged_cache_cap entry count remains the secondary bound.
        import collections

        from pixie_tpu.serving.residency import ResidencyPool

        self._staged_cache = ResidencyPool()
        # Shared scans (r12, flag shared_scans): concurrent queries whose
        # fold signatures match coalesce into one device dispatch; the
        # followers reuse the leader's merged states and run only their
        # own finalize (serving/shared_scan.py).
        from pixie_tpu.serving.shared_scan import SharedScanCoordinator

        self._shared_scans = SharedScanCoordinator()
        # Optional serving/signatures.FoldSignatureStore: successful
        # device aggregations with replayable shapes are recorded per
        # table, and prewarm_table replays them across restarts instead
        # of guessing the canonical count+sum(f64) shape (r12 satellite).
        self.fold_signature_store = None
        # Device-resident incremental ingest (r13, flag resident_ingest):
        # per-table HBM ring windows fed by table appends
        # (serving/resident.py), created lazily on enable so the manager
        # costs nothing when the flag is off.
        self._resident = None
        # Host-densified key plans per (table version, key exprs), LRU.
        self._keyplan_cache: "collections.OrderedDict[tuple, Any]" = (
            collections.OrderedDict()
        )
        self._keyplan_cache_cap = flags.keyplan_cache_cap
        # Offload is best-effort; failures fall back to the host engine but
        # must stay observable (one log per distinct error signature).
        self.fallback_errors: dict[str, str] = {}
        # Streaming-stage failures fall back to MONOLITHIC staging (still
        # on-device), tracked separately so fallback_errors keeps meaning
        # "query left the mesh".
        self.stream_fallback_errors: dict[str, str] = {}
        # (uda set, capacity) -> (finalize modes, packed-output templates).
        self._finmode_cache: dict[tuple, Any] = {}
        # AOT-compiled fold executables (sig -> jax Compiled) + the single
        # background thread that lowers/compiles them while staging
        # streams (the r7 compile/staging overlap). _aot_futures tracks
        # in-flight compiles so a query arriving mid-compile attaches to
        # the running future instead of compiling twice; _prewarmed holds
        # the fold signatures speculatively compiled at table-create time
        # (r8 prewarm_compile) so hits are attributable (prewarm_hit).
        self._aot_compiled: dict[str, Any] = {}
        self._aot_futures: dict[str, Any] = {}
        self._prewarmed: set[str] = set()
        self.prewarm_errors: dict[str, str] = {}
        self._aot_pool = None
        # Host-computed any() representatives, keyed by
        # (table, version, window, key exprs, col); small LRU.
        self._hostany_cache: "collections.OrderedDict[tuple, np.ndarray]" = (
            collections.OrderedDict()
        )
        # Circuit breaker (r9): per program-key [consecutive_failures,
        # open_until_monotonic]. device_breaker_threshold consecutive
        # fold/compile failures trip the key to the host engine for
        # device_breaker_cooldown_s; the first post-cooldown attempt is
        # the half-open trial — one more failure re-opens immediately,
        # a success closes the breaker.
        self._breaker: dict[str, list] = {}
        self._breaker_lock = threading.Lock()
        # Last successful device-fold wall time (ms) for the health plane.
        self.last_fold_ms: "float | None" = None
        # Per-program-key fold-latency reservoir (r11): the health plane
        # publishes live p50/p99 per query shape on every heartbeat, so
        # /statusz shows per-phase percentiles without running a query.
        self._fold_lat: dict[str, "collections.deque"] = {}
        self._fold_lat_lock = threading.Lock()
        # Mesh recovery plane (r23): the geometry degradation ladder
        # (full geometry first, flat last, None = host engine), built
        # meshes cached per rung — restoring a rung reuses the SAME
        # Mesh object, so resident-ring/mesh identity checks hold on
        # recovery — a per-geometry breaker keyed by mesh signature
        # (repeat offenders skip straight to the degraded rung, with
        # half-open recovery back to full geometry), and window-level
        # fold checkpoints keyed by geometry-FREE fold identity (a
        # resume lands on a different rung by construction).
        self._geom_lock = threading.RLock()
        self._full_mesh_config = self.mesh_config
        self._geom_ladder = self.mesh_config.ladder()
        self._rung_meshes = {self._mesh_sig: self.mesh}
        self._geom_breaker: dict[str, list] = {}
        self._fold_ckpt: "collections.OrderedDict[str, dict]" = (
            collections.OrderedDict()
        )
        self._geom_events = {
            "degrade": 0,
            "checkpoint_windows": 0,
            "resumes": 0,
            "recovered_folds": 0,
        }
        # Window accounting of the most recent checkpoint resume
        # (bench config 12 reads the refolded-window fraction here).
        self.last_resume_stats: "dict | None" = None
        # Fold signatures that completed at least one multi-axis
        # dispatch on this executor: the DERIVED watchdog deadline only
        # arms for these — a first dispatch may compile inline (AOT
        # miss / monolithic fallback), and a cost-model prediction of
        # steady-state fold wall says nothing about compile time.
        self._warm_dispatch_sigs: set = set()
        # Worst multi-axis dispatch wall observed on this executor
        # (abandoned dispatches report theirs too): the derived
        # watchdog deadline rails over this as well as the model's
        # solo prediction, so a loaded process does not read its own
        # ambient slowness as a hang.
        self._dispatch_wall_max = 0.0

    # -- public -------------------------------------------------------------
    @staticmethod
    def _breaker_key(fragment: PlanFragment) -> str:
        """Structural program key for the circuit breaker: the operator
        chain + table names, NOT the table version — a poisoned fold shape
        must stay tripped across data growth, while a different query
        shape keeps its own healthy breaker. Shared with the broker's
        health plane (plan/program_key.py) so heartbeat-reported breaker
        keys match what planning computes."""
        from pixie_tpu.plan.program_key import fragment_program_key

        return fragment_program_key(fragment)

    def breaker_snapshot(self) -> dict[str, dict]:
        """Per-program-key breaker state for the health plane:
        ``key -> {state: open|half_open|degrading, failures,
        open_remaining_s}``. Healthy keys are absent (success pops the
        entry), so the snapshot is empty on a healthy executor and
        heartbeats stay small."""
        threshold = flags.device_breaker_threshold
        if threshold <= 0:
            return {}
        now = time.monotonic()
        out = {}
        with self._breaker_lock:
            for key, (fails, open_until) in self._breaker.items():
                if open_until > now:
                    state = "open"
                elif open_until > 0:
                    # Cooldown elapsed; the next attempt is the half-open
                    # trial — planners should treat the key as usable.
                    state = "half_open"
                else:
                    state = "degrading"  # failures below the trip threshold
                out[key] = {
                    "state": state,
                    "failures": fails,
                    "open_remaining_s": round(max(0.0, open_until - now), 3),
                }
        return out

    def _record_fold_latency(self, key: str, ms: float) -> None:
        with self._fold_lat_lock:
            dq = self._fold_lat.get(key)
            if dq is None:
                dq = self._fold_lat[key] = collections.deque(maxlen=256)
            dq.append(ms)

    def fold_latency_snapshot(self) -> dict[str, dict]:
        """program_key -> {p50_ms, p99_ms, n} over the recent fold-latency
        reservoir (r11; rides heartbeats into the broker's health plane
        and /statusz)."""
        out = {}
        with self._fold_lat_lock:
            items = [(k, sorted(dq)) for k, dq in self._fold_lat.items()]
        for key, lat in items:
            if not lat:
                continue
            out[key] = {
                "p50_ms": round(lat[len(lat) // 2], 3),
                "p99_ms": round(lat[min(len(lat) - 1,
                                        int(len(lat) * 0.99))], 3),
                "n": len(lat),
            }
        return out

    def health_snapshot(self) -> dict:
        """Device-executor health riding agent heartbeats (r10): breaker
        state per program key, open keys (what planning matches on),
        background-compile queue depth, the last device-fold wall time,
        and (r11) per-program-key fold-latency percentiles."""
        snap = self.breaker_snapshot()
        return {
            "breaker": snap,
            "breaker_open": sorted(
                k for k, v in snap.items() if v["state"] == "open"
            ),
            "staging_depth": len(self._aot_futures),
            "last_fold_ms": self.last_fold_ms,
            "fold_latency": self.fold_latency_snapshot(),
            # HBM residency (r12): staged/pinned bytes vs hbm_budget_mb
            # ride heartbeats so the broker's admission controller and
            # /statusz see device residency without touching the device.
            "residency": self._staged_cache.snapshot(),
            # Resident-ingest rings (r13): windows/bytes per hot table.
            "resident_ingest": (
                self._resident.snapshot() if self._resident else {}
            ),
            # Adopted replica rings (r17): per-table window coverage,
            # leader watermark, and lag — the broker's failover ranking
            # prefers agents whose replicas already hold the data.
            "replicas": (
                self._resident.replica_snapshot() if self._resident else {}
            ),
            # Mesh recovery plane (r23): active vs full geometry, the
            # degradation ladder, per-geometry breaker, and the
            # degrade/checkpoint/resume event counts.
            "mesh": self.mesh_recovery_snapshot(),
        }

    # -- device-resident incremental ingest (r13) ----------------------------
    def enable_resident_ingest(self, table):
        """Attach an HBM ring to ``table``'s appends (flag
        ``resident_ingest``; wired from the table store's create
        listener so every new table opts in automatically). Returns the
        ring or None."""
        if not flags.resident_ingest:
            return None
        return self._resident_manager().enable(table)

    def _resident_manager(self):
        if self._resident is None:
            from pixie_tpu.serving.resident import ResidentIngestManager

            self._resident = ResidentIngestManager(
                self.mesh, self.block_rows, self._staged_cache
            )
        return self._resident

    # -- ring replication (r17) ----------------------------------------------
    def set_ring_replication_hook(self, hook) -> None:
        """Leader side: install ``hook(table, k, start_row, rows,
        wire_cols, latest_k)`` on every owned ring (current and future)
        — the agent's replicator ships each staged window's encoded
        payload to follower agents."""
        self._resident_manager().set_replication_hook(hook)

    def adopt_replica_window(
        self, table_name, window_rows, k, start_row, rows, wire_cols,
        latest_k,
    ) -> bool:
        """Follower side: decode one replicated ring window into this
        executor's HBM (byte-accounted in the residency pool). Works
        without ``resident_ingest`` — a follower never owns the
        table's appends."""
        return self._resident_manager().adopt_replica_window(
            table_name, window_rows, k, start_row, rows, wire_cols,
            latest_k,
        )

    def replica_snapshot(self) -> dict:
        return (
            self._resident.replica_snapshot() if self._resident else {}
        )

    def _resident_ring(self, table, src_op):
        """The table's ring when the resident fast path applies: a ring
        exists and the query has no time bounds (the row-id↔window
        alignment the ring serves assumes the cursor returns every
        resident row). With ``resident_ingest`` off, only ADOPTED
        replica rings serve (r17 failover: the follower never observes
        appends, so the flag gating owned ingest does not apply)."""
        if self._resident is None:
            return None
        if self._resident.mesh is not self.mesh:
            # Degraded geometry (r23): ring windows are sharded on the
            # full mesh. They serve again when the breaker's half-open
            # trial restores that rung (same Mesh object, cached).
            return None
        if src_op.start_time is not None or src_op.stop_time is not None:
            return None
        if flags.resident_ingest:
            return self._resident.ring_for(src_op.table_name)
        return self._resident.replica_for(src_op.table_name)

    def _decode_fn(self, plan, cp, cache: dict):
        """Resolve a window decode program: the background-AOT-compiled
        executable when its compile already landed, else the in-line
        jit (first call compiles; an AOT failure is recorded in
        stream_fallback_errors like a fold-compile failure)."""
        from pixie_tpu.ops import codec as _codec

        sig = f"decode|{cp.sig()}|mesh:{self._mesh_sig}"
        fn = cache.get(sig)
        if fn is not None:
            return fn
        fn = _codec.decoder(self.mesh, cp, plan.nblk, plan.b)
        fut = self._aot_futures.get(sig)
        done = self._aot_compiled.get(sig)
        if done is not None:
            fn = done
        elif fut is not None and fut.done():
            try:
                fn = fut.result()
            except Exception as e:
                key = f"decode-aot {type(e).__name__}: {e}"
                if key not in self.stream_fallback_errors:
                    import traceback

                    self.stream_fallback_errors[key] = (
                        traceback.format_exc()
                    )
        cache[sig] = fn
        return fn

    def _kick_decode_aot(self, plan) -> None:
        """Queue the plan's decode programs on the AOT worker so they
        compile concurrently with the first windows' pack/transfer."""
        from pixie_tpu.ops import codec as _codec

        if not flags.aot_compile:
            return
        for cp in plan.codecs.values():
            sig = f"decode|{cp.sig()}|mesh:{self._mesh_sig}"
            if sig in self._aot_compiled or sig in self._aot_futures:
                continue
            try:
                # Own breakdown key (r16): stage_compile stays the FOLD
                # compile signal (the r8 prewarm contract asserts it
                # zero on a prewarm hit — a column codec engaging must
                # not look like a fold recompile).
                self._aot_compile_async(
                    sig,
                    _codec.decoder(self.mesh, cp, plan.nblk, plan.b),
                    _codec.decode_avals(cp, self.mesh),
                    profile_key="decode_compile",
                )
            except Exception:
                pass  # best-effort: the in-line jit path still works

    def _put_window_cols(self, plan, packed, col_names, dec_cache):
        """device_put one window's packed columns: passthrough blocks
        transfer as-is; CodecPayload columns transfer their (much
        smaller) encoded arrays and expand on device (stage_decode).
        Either way the resulting block is bit-identical."""
        from pixie_tpu.ops import codec as _codec

        axis_name = self.mesh_axes  # full axis tuple: dim0 over every mesh axis
        sharding = NamedSharding(self.mesh, P(axis_name))
        dev_cols = {}
        for n2 in col_names:
            p = packed[n2]
            if isinstance(p, _codec.CodecPayload):
                args = _codec.put_payload(self.mesh, p)
                t0 = time.perf_counter()
                dev_cols[n2] = self._decode_fn(plan, p.plan, dec_cache)(
                    *args
                )
                COLD_PROFILE["stage_decode"] = COLD_PROFILE.get(
                    "stage_decode", 0.0
                ) + (time.perf_counter() - t0)
            else:
                dev_cols[n2] = jax.device_put(p, sharding)
        return dev_cols

    def _convert_resident_window(self, plan, rw, col_names):
        """Raw-dtype ring blocks → the plan's block dtypes, ON DEVICE
        (ops/codec.py converters reproduce the host pack transform bit
        for bit). Zero wire bytes: this is the resident-ingest hot
        path."""
        from pixie_tpu.ops import codec as _codec

        t0 = time.perf_counter()
        dev_cols = {}
        for n2 in col_names:
            blk = rw.blocks[n2]
            kind = plan.col_plans[n2][0]
            if kind == "raw" and blk.dtype == plan.block_dtypes[n2]:
                dev_cols[n2] = blk  # identity: serve the ring block itself
                continue
            dev_cols[n2] = _codec.convert_block(
                self.mesh,
                plan.col_plans[n2],
                blk,
                int_dtype=plan.block_dtypes[n2],
            )
        COLD_PROFILE["stage_resident_convert"] = COLD_PROFILE.get(
            "stage_resident_convert", 0.0
        ) + (time.perf_counter() - t0)
        COLD_PROFILE["stage_resident_hits"] = COLD_PROFILE.get(
            "stage_resident_hits", 0.0
        ) + 1.0
        return dev_cols

    def _breaker_is_open(self, key: str) -> bool:
        threshold = flags.device_breaker_threshold
        if threshold <= 0:
            return False
        with self._breaker_lock:
            st = self._breaker.get(key)
            return st is not None and st[1] > time.monotonic()

    def _breaker_record(self, key: str, ok: bool) -> None:
        threshold = flags.device_breaker_threshold
        if threshold <= 0:
            return
        with self._breaker_lock:
            if ok:
                self._breaker.pop(key, None)  # success closes the breaker
                return
            st = self._breaker.setdefault(key, [0, 0.0])
            st[0] += 1
            if st[0] >= threshold:
                # Trip (or re-trip after a failed half-open trial): route
                # this key to the host engine for the cooldown.
                st[1] = time.monotonic() + flags.device_breaker_cooldown_s
                _BREAKER_TRIPS.inc()
                import logging

                logging.getLogger("pixie_tpu.parallel").warning(
                    "device circuit breaker OPEN for %.1fs after %d "
                    "consecutive failures (key %.80s...)",
                    flags.device_breaker_cooldown_s, st[0], key,
                )

    # -- mesh geometry recovery (r23) ----------------------------------------
    def _geom_breaker_open(self, sig: str) -> bool:
        threshold = flags.mesh_breaker_threshold
        if threshold <= 0:
            return False
        with self._geom_lock:
            st = self._geom_breaker.get(sig)
            return st is not None and st[1] > time.monotonic()

    def _geom_breaker_record(self, sig: str, ok: bool) -> None:
        threshold = flags.mesh_breaker_threshold
        if threshold <= 0:
            return
        with self._geom_lock:
            if ok:
                self._geom_breaker.pop(sig, None)  # success closes it
                return
            st = self._geom_breaker.setdefault(sig, [0, 0.0])
            st[0] += 1
            if st[0] >= threshold:
                # Open (or re-open after a failed half-open trial): new
                # folds skip this rung for the cooldown; the first
                # post-cooldown fold is the half-open trial back toward
                # full geometry.
                st[1] = time.monotonic() + flags.mesh_breaker_cooldown_s
                import logging

                logging.getLogger("pixie_tpu.parallel").warning(
                    "mesh geometry breaker OPEN for %.1fs: %s failed %d "
                    "consecutive folds; new folds start on the next "
                    "degradation rung",
                    flags.mesh_breaker_cooldown_s, sig, st[0],
                )

    def mesh_breaker_snapshot(self) -> dict[str, dict]:
        """Per-geometry breaker state (mirrors ``breaker_snapshot``):
        ``mesh_sig -> {state, failures, open_remaining_s}``."""
        if flags.mesh_breaker_threshold <= 0:
            return {}
        now = time.monotonic()
        out = {}
        with self._geom_lock:
            for sig, (fails, open_until) in self._geom_breaker.items():
                if open_until > now:
                    state = "open"
                elif open_until > 0:
                    state = "half_open"
                else:
                    state = "degrading"
                out[sig] = {
                    "state": state,
                    "failures": fails,
                    "open_remaining_s": round(max(0.0, open_until - now), 3),
                }
        return out

    def mesh_recovery_snapshot(self) -> dict:
        """The r23 recovery plane's health section (rides heartbeats and
        /statusz): active vs full geometry, the degradation ladder, the
        per-geometry breaker, and the degrade/checkpoint/resume counts
        that make every recovery auditable."""
        with self._geom_lock:
            full = self._full_mesh_config.signature()
            return {
                "geometry": self._mesh_sig,
                "full_geometry": full,
                "degraded": self._mesh_sig != full,
                "ladder": [
                    c.signature() if c is not None else "host"
                    for c in self._geom_ladder
                ],
                "breaker": self.mesh_breaker_snapshot(),
                "degrade_events": self._geom_events["degrade"],
                "checkpoint_windows": self._geom_events["checkpoint_windows"],
                "checkpoint_resumes": self._geom_events["resumes"],
                "recovered_folds": self._geom_events["recovered_folds"],
                "checkpoints_held": len(self._fold_ckpt),
            }

    def _activate_geometry(self, cfg: "mesh_lib.MeshConfig") -> None:
        """Point the executor at ``cfg``'s mesh. Rung meshes are cached,
        so restoring a rung reuses the ORIGINAL Mesh object (resident
        rings resume serving on mesh identity, not equality). Staged
        cache entries re-place lazily at lookup via the partition-rule
        tree; compiled programs carry the geometry signature, so a
        stale executable can never dispatch on the new mesh."""
        with self._geom_lock:
            sig = cfg.signature()
            if sig == self._mesh_sig:
                return
            mesh = self._rung_meshes.get(sig)
            if mesh is None:
                mesh = cfg.build()
                self._rung_meshes[sig] = mesh
            self.mesh = mesh
            self.mesh_config = cfg
            self.mesh_axes = mesh_lib.data_axes(mesh)
            self._mesh_sig = sig

    def _execute_with_recovery(
        self, fragment, table_store, registry, func_ctx
    ):
        """Walk the geometry degradation ladder (r23): start at the
        first rung whose per-geometry breaker is closed (an expired
        cooldown makes the attempt the half-open trial), and on a
        recoverable ``MeshGeometryError`` (host loss, hung collective)
        re-plan the SAME fold one rung down — the retried answer is
        bit-identical by the r21 invariant, and a window checkpoint
        (flag ``mesh_fold_checkpoint``) lets the stream resume instead
        of refolding. A non-recoverable error or an exhausted ladder
        propagates to the caller's host-engine fallback."""
        rungs = self._geom_ladder
        last_err = None
        for i, cfg in enumerate(rungs):
            if cfg is None:
                break  # past the mesh: host engine
            sig = cfg.signature()
            if self._geom_breaker_open(sig):
                continue
            if sig != self._mesh_sig:
                self._activate_geometry(cfg)
            try:
                out = self._try_execute_fragment(
                    fragment, table_store, registry, func_ctx
                )
                self._geom_breaker_record(sig, ok=True)
                if last_err is not None and out is not None:
                    with self._geom_lock:
                        self._geom_events["recovered_folds"] += 1
                return out
            except mesh_lib.MeshGeometryError as e:
                if not e.recoverable:
                    raise  # signature mismatch etc: host fallback
                self._geom_breaker_record(sig, ok=False)
                _MESH_DEGRADE.inc()
                with self._geom_lock:
                    self._geom_events["degrade"] += 1
                nxt = next(
                    (
                        r.signature()
                        for r in rungs[i + 1:]
                        if r is not None
                    ),
                    "host",
                )
                if trace.ACTIVE:
                    trace.record(
                        "mesh.recover",
                        0,
                        attrs={"kind": e.kind, "from": sig, "to": nxt},
                    )
                import logging

                logging.getLogger("pixie_tpu.parallel").warning(
                    "mesh geometry failure [%s] on %s: re-planning the "
                    "fold on %s",
                    e.kind, sig, nxt,
                )
                last_err = e
        if last_err is not None:
            raise last_err
        return None

    def _watchdog_deadline(self, fold_sig=None, warm=True) -> "float | None":
        """Collective-watchdog deadline for one sharded dispatch, or
        None (no watchdog). The flag wins when positive; 0 derives the
        deadline from the r22 CostModel prediction x the rail factor
        (no opinion = no watchdog — a deadline must come from evidence);
        negative disables outright. A derived deadline additionally
        requires ``warm`` — this signature already completed a dispatch
        here — because a cold dispatch may compile inline and the model
        predicts steady-state fold wall, not XLA compile time."""
        t = float(flags.mesh_dispatch_timeout_s)
        if t > 0:
            return t
        if t < 0 or not warm:
            return None
        cm = _cost_model()
        if not cm.ACTIVE:
            return None
        pred = (
            cm.predict_seconds(sig=fold_sig)
            if fold_sig is not None
            else cm.predict_seconds(family="fold")
        )
        if not pred or pred <= 0:
            return None
        # Floor keeps a microsecond-scale prediction from tripping on
        # ordinary scheduler jitter, and the worst dispatch wall seen
        # locally x4 keeps ambient load from masquerading as a hang:
        # the model predicts SOLO wall, but this process may be running
        # clients, agents, and a second executor on the same cores. The
        # watchdog hunts HANGS — a hang is unbounded, 4x the slowest
        # completed dispatch is not.
        return max(
            0.25,
            pred * float(flags.mesh_watchdog_rail_factor),
            self._dispatch_wall_max * 4.0,
        )

    def _mesh_dispatch(self, fn, what: str = "fold", fold_sig=None):
        """Run one synchronizing sharded dispatch under the recovery
        plane (r23): deterministic fault sites first (``mesh.host_loss``
        / ``mesh.collective_timeout`` — from inside one process a dead
        host and a hung collective both look like a dispatch that never
        completes, so both inject here), then the collective watchdog —
        the dispatch runs on a reaper thread and a deadline miss raises
        a detected ``MeshGeometryError`` instead of hanging the query
        (the stuck thread is abandoned; it holds no executor locks,
        only the process-wide collective lock — see _watchdog_run).
        Every multi-axis dispatch serializes on _MESH_COLLECTIVE_LOCK:
        two interleaved all-device collective programs deadlock the
        shared pool. Single-axis meshes have no hosts to lose and no
        cross-host collectives: plain call. The disabled path (flat
        mesh, or no armed site and no deadline) is a handful of
        attribute reads — microbench_fault_overhead holds it under
        1%."""
        if len(self.mesh_config.axes) > 1:
            if faults.ACTIVE:
                if faults.fires("mesh.host_loss"):
                    raise mesh_lib.MeshGeometryError(
                        "host_loss", f"{what} on {self._mesh_sig}"
                    )
                if faults.fires("mesh.collective_timeout"):
                    raise mesh_lib.MeshGeometryError(
                        "collective_timeout", f"{what} on {self._mesh_sig}"
                    )
            deadline = self._watchdog_deadline(
                fold_sig, warm=fold_sig in self._warm_dispatch_sigs
            )
            if deadline is not None:
                out = self._watchdog_run(deadline, fn, what)
            else:
                with _MESH_COLLECTIVE_LOCK:
                    t0 = time.perf_counter()
                    # Dispatch is ASYNC even on CPU: fn() returns once
                    # the program is enqueued. Block before releasing
                    # the lock or the next all-device program overlaps
                    # this one's still-running collectives and wedges
                    # the rendezvous.
                    out = jax.block_until_ready(fn())
                    self._note_dispatch_wall(time.perf_counter() - t0)
            if fold_sig is not None:
                self._warm_dispatch_sigs.add(fold_sig)
            return out
        if len(self._full_mesh_config.axes) > 1:
            # Degraded-rung dispatch of a multi-axis executor: the flat
            # program still rendezvouses every device, so it must not
            # interleave with an abandoned (timed-out) full-geometry
            # program that is draining on the same pool — queue behind
            # it. Executors that were BORN flat never take the lock.
            with _MESH_COLLECTIVE_LOCK:
                return jax.block_until_ready(fn())
        return fn()

    def _note_dispatch_wall(self, wall: float) -> None:
        if wall > self._dispatch_wall_max:
            self._dispatch_wall_max = wall

    def _watchdog_run(self, deadline: float, fn, what: str):
        from pixie_tpu.ops import segment as _segment

        box: dict = {}
        platform = self.mesh.devices.flat[0].platform
        started = threading.Event()
        done = threading.Event()

        def run():
            # The collective lock is taken ON the reaper thread so an
            # abandoned (timed-out) dispatch keeps holding it until its
            # collective actually returns: overlapping a fresh
            # all-device program with a wedged one deadlocks the whole
            # pool, which is strictly worse than queueing behind it.
            with _MESH_COLLECTIVE_LOCK:
                started.set()
                t0 = time.perf_counter()
                try:
                    # First call may trace: carry the caller's platform
                    # hint onto the reaper thread so lane strategy
                    # stays pinned. block_until_ready: dispatch is
                    # async — the lock must outlive the EXECUTION, not
                    # just the enqueue (see _mesh_dispatch).
                    with _segment.platform_hint(platform):
                        box["value"] = jax.block_until_ready(fn())
                except BaseException as e:  # re-raised on the caller
                    box["error"] = e
                finally:
                    # Recorded even when the caller already gave up on
                    # this dispatch: a false trip (slow-but-healthy
                    # collective) raises the observed rail, so the NEXT
                    # deadline clears it — one bad prediction cannot
                    # cascade.
                    self._note_dispatch_wall(time.perf_counter() - t0)
                    done.set()

        th = threading.Thread(target=run, name="mesh-watchdog", daemon=True)
        th.start()
        # Queue wait is NOT a hang: the deadline times the exclusive
        # execution window only — concurrent dispatches line up on the
        # collective lock, and a cost-model prediction knows nothing
        # about the queue in front of this one.
        started.wait()
        if not done.wait(timeout=deadline):
            raise mesh_lib.MeshGeometryError(
                "collective_timeout",
                f"{what} exceeded the {deadline:.3f}s watchdog deadline "
                f"on {self._mesh_sig}",
            )
        if "error" in box:
            raise box["error"]
        return box["value"]

    def _staged_mesh_ok(self, staged) -> bool:
        """False when a cached staging's shards live on a different mesh
        than the executor's current one (a degradation rung switched
        geometry since it staged)."""
        for a in staged.blocks.values():
            sh = getattr(a, "sharding", None)
            if sh is None:
                return True
            try:
                return sh.mesh == self.mesh or sh.mesh is self.mesh
            except Exception:
                return True
        return True

    def _save_fold_checkpoint(self, key, windows_done, host_state) -> None:
        with self._geom_lock:
            self._fold_ckpt[key] = {
                "windows": int(windows_done),
                "state": host_state,
            }
            self._fold_ckpt.move_to_end(key)
            while len(self._fold_ckpt) > 4:
                self._fold_ckpt.popitem(last=False)
            self._geom_events["checkpoint_windows"] += 1
        _MESH_CKPT_WINDOWS.inc()

    def _load_fold_checkpoint(self, key, leaves, d, sharding):
        """Validated checkpoint state for ``key``, device_put onto the
        CURRENT mesh (bit-exact: the pull was a host copy of per-device
        carry state, and every rung keeps the device count, so shapes
        are unchanged). Returns (flat_state, windows_done) or (None, 0).
        A corrupt checkpoint — injected, or a shape/dtype mismatch
        against the fold's state template — is DISCARDED and the fold
        restarts from scratch: never resurrect bad carry state (r14
        RingSpill posture)."""
        with self._geom_lock:
            ck = self._fold_ckpt.get(key)
        if ck is None:
            return None, 0
        corrupt = faults.ACTIVE and faults.fires("mesh.checkpoint_corrupt")
        if not corrupt:
            st = ck["state"]
            if len(st) != len(leaves):
                corrupt = True
            else:
                for a, leaf in zip(st, leaves):
                    if a.shape != (d,) + tuple(leaf.shape) or (
                        a.dtype != leaf.dtype
                    ):
                        corrupt = True
                        break
        if corrupt:
            import logging

            with self._geom_lock:
                self._fold_ckpt.pop(key, None)
            logging.getLogger("pixie_tpu.parallel").warning(
                "discarding corrupt mesh fold checkpoint (refolding "
                "from scratch, never resuming bad carry state)"
            )
            return None, 0
        state = [jax.device_put(a, sharding) for a in ck["state"]]
        return state, int(ck["windows"])

    def try_execute_fragment(
        self, fragment: PlanFragment, table_store, registry, func_ctx=None
    ) -> Optional[tuple[int, RowBatch]]:
        """If the fragment contains the hot chain, run it on the mesh and
        return (agg_node_id, finalized agg RowBatch); else None — including
        when any stage of device planning/tracing fails (host-untraceable
        expressions, dictionary edge cases): offload is an optimization,
        never a correctness cliff.

        Circuit breaker (r9): device_breaker_threshold consecutive
        failures for one program key skip the device entirely for
        device_breaker_cooldown_s (no repeated staging/compile churn on a
        poisoned shape), surfaced via the device_offload_fallback metric
        family (..._breaker_trips_total / ..._breaker_open_total)."""
        bkey = self._breaker_key(fragment)
        if self._breaker_is_open(bkey):
            _BREAKER_SKIPS.inc()
            _OFFLOAD_FALLBACKS.inc()
            return None
        try:
            t0 = time.perf_counter_ns()
            # r23: the fold runs under the geometry degradation ladder —
            # a host loss or hung collective re-plans the same fold on
            # the next surviving geometry (bit-identical) before the
            # host engine is ever considered.
            out = self._execute_with_recovery(
                fragment, table_store, registry, func_ctx
            )
            (_OFFLOAD_HITS if out is not None else _OFFLOAD_MISS).inc()
            if out is not None:
                self._breaker_record(bkey, ok=True)
                elapsed_ns = time.perf_counter_ns() - t0
                self.last_fold_ms = elapsed_ns / 1e6
                self._record_fold_latency(bkey, self.last_fold_ms)
                if trace.ACTIVE:
                    # The whole device offload (stage hit/miss + fold +
                    # finalize) as one span; per-phase children come from
                    # the staging/stream profiling hooks.
                    trace.record(
                        "device.execute",
                        elapsed_ns,
                        attrs={"program_key": bkey[:120]},
                    )
                if resattr.ACTIVE:
                    # r15: the offload as one attributed dispatch row —
                    # joins device wall time to the ambient
                    # (query_id, tenant) in device_dispatches.
                    resattr.record_dispatch(
                        "fold", elapsed_ns / 1e9, program=bkey[:120]
                    )
                cm = _cost_model()
                if cm.ACTIVE:
                    # r22: the whole-offload wall feeds the shapeless
                    # ``fold`` cost family — the controller's predictive
                    # term and admission's fold-seconds advisory.
                    cm.observe_family("fold", 0, elapsed_ns / 1e9)
            return out
        except Exception as e:
            import logging
            import traceback

            _OFFLOAD_FALLBACKS.inc()
            self._breaker_record(bkey, ok=False)
            key = f"{type(e).__name__}: {e}"
            if key not in self.fallback_errors:
                self.fallback_errors[key] = traceback.format_exc()
                logging.getLogger("pixie_tpu.parallel").warning(
                    "device offload failed, falling back to host engine: %s",
                    key,
                )
            return None

    def _try_execute_fragment(
        self, fragment: PlanFragment, table_store, registry, func_ctx=None
    ) -> Optional[tuple[int, RowBatch]]:
        table_rel = lambda op: table_store.get_relation(op.table_name)
        relations = fragment.resolve_relations(registry, table_rel)
        m = match_fragment(fragment, relations)
        if m is None:
            ja = self._try_execute_join_agg(
                fragment, relations, table_store, registry, func_ctx
            )
            if ja is not None:
                return ja
            # r19: join-agg decomposition first (it never materializes the
            # pairs), then the standalone sort-merge join lane.
            dj = self._try_execute_join(
                fragment, relations, table_store, registry, func_ctx
            )
            if dj is not None:
                return dj
            return self._try_execute_scan(
                fragment, relations, table_store, registry, func_ctx
            )
        table = table_store.get_table(m.source_op.table_name)
        if table is None:
            return None
        # Fault site: poison the device fold dispatch for a matched
        # fragment (chaos tests prove the fallback is bit-identical on the
        # host engine and the circuit breaker trips after N hits).
        if faults.ACTIVE:
            faults.check("pipeline.fold")

        specs = self._agg_specs(m, registry)
        if specs is None:
            return None
        evaluator = self._make_evaluator(m, specs, registry, func_ctx)
        if evaluator is None:
            return None

        windowed = m.agg_op.windowed and m.agg_op.stage == AggStage.FULL
        # Host-side any() candidates are syntactic (no predicates, bare
        # column): their arg columns never ship to HBM — exclude them from
        # base_cols up front; if planning falls through after the key plan
        # resolves, they rejoin the device path below.
        any_candidates = set()
        if not m.predicates and m.agg_op.stage == AggStage.FULL and (
            not windowed  # reps would need a per-window pass: device path
        ):
            any_candidates = {
                out
                for out, arg_e, uda in specs
                if uda.name == "any"
                and uda.reads_args
                and isinstance(arg_e, ColumnRef)
            }
        # Host: read needed source columns. UDAs that never read their
        # column (count) contribute nothing — staging their arg would ship
        # gigabytes of unread data to HBM.
        base_cols = set()
        for e in m.predicates:
            base_cols |= referenced_columns(e)
        for out, e, uda in specs:
            if uda.reads_args and out not in any_candidates:
                base_cols |= referenced_columns(e)
        with _timed("plan_keys"):
            key_plan = self._plan_keys(m, table, registry, func_ctx, base_cols)
        if key_plan is None:
            return None
        base_groups = max(key_plan.num_groups, 1)
        n_windows = 1
        if windowed:
            # Window id = one more (leading) group axis: gid' = wid*G+gid,
            # windows cut at the cursor's eow markers — the same
            # boundaries the host AggNode emits on (agg_node.py:242).
            wk = self._windowize_key_plan(m, table, key_plan, base_groups)
            if wk is None:
                return None
            key_plan, n_windows = wk
        with _timed("host_any"):
            host_any = (
                self._plan_host_any(m, specs, key_plan, table)
                if any_candidates
                else {}
            )
        for out, e, uda in specs:
            if out in any_candidates and out not in host_any:
                # Host-side plan fell through (no usable gid source):
                # back to the device path — its column must stage.
                base_cols |= referenced_columns(e)
        device_specs = [s for s in specs if s[0] not in host_any]
        capacity_hint, _ = self._pass_plan(device_specs, key_plan.num_groups)
        cell_cols = self._cell_cols(m, device_specs, capacity_hint)
        # The key signature must pin the actual group expressions — two
        # queries over the same table version with different groupbys must
        # not share staged gids.
        key_sig = repr(
            [m.col_exprs[g] for g in m.agg_op.groups]
        ) + (
            ":host" if key_plan.host_gids is not None
            else (":lut" if isinstance(key_plan.device_expr, tuple) else ":dev")
        ) + (f":win{n_windows}" if windowed else "")
        # Version = (min_row_id, end_row_id): writes bump end_row_id and
        # ring-buffer expiry bumps min_row_id, so either invalidates.
        version = (table.min_row_id(), table.end_row_id())
        # f32-staged sketch columns participate in the cache identity: an
        # exact f64 aggregation must never reuse a staging narrowed for a
        # sketch-only query (silently f32-truncated sums otherwise).
        f32_cols = self._sketch_f32_cols(m, specs)
        # Staged HOST gids derived from mutable metadata state (needs_ctx
        # UDFs) must never be cached — pod/service mappings churn without
        # table writes. The device-LUT key path is safe: staged blocks hold
        # raw codes and the LUT is recomputed and passed as an argument.
        cacheable = key_plan.host_gids is None or not any(
            _uses_ctx_func(m.col_exprs[g], m.source_relation, registry)
            for g in m.agg_op.groups
        )
        cache_key = (
            m.source_op.table_name,
            version,
            tuple(sorted(base_cols)),
            m.source_op.start_time,
            m.source_op.stop_time,
            self.block_rows,
            key_sig,
            key_plan.num_groups,
            tuple(sorted(f32_cols)),
            # name AND cardinality bound: two queries with different
            # pass capacities must not share codes staged under a
            # different max_card (their cell-lane segment budgets differ).
            tuple(sorted(cell_cols.items())),
        )
        staged = self._staged_cache.get(cache_key) if cacheable else None
        if staged is None and cacheable:
            # Superset reuse: an entry staged for a wider column set of the
            # SAME table version/window/key plan serves this query directly
            # (the program reads the columns it needs) — re-staging
            # gigabytes for a subset risks doubling HBM residency.
            for k, v in self._staged_cache.items():
                if (
                    k[0] == cache_key[0]
                    and k[1] == cache_key[1]
                    and set(k[2]) >= set(cache_key[2])
                    and k[3:] == cache_key[3:]
                ):
                    cache_key = k
                    staged = v
                    break
        if staged is not None and not self._staged_mesh_ok(staged):
            # Geometry changed since this entry staged (an r23
            # degradation rung, or a half-open recovery back to full):
            # re-place its shards onto the current mesh through the
            # partition-rule tree — same bytes, no host restage. The
            # old entry retires (zombie while a concurrent fold on the
            # old mesh still pins it).
            from pixie_tpu.parallel import staging as _staging_mod

            with _timed("stage_repartition"):
                staged = _staging_mod.repartition_staged(self.mesh, staged)
            if cacheable:
                self._staged_insert(
                    cache_key, staged, m.source_op.table_name, version
                )
        if staged is not None:
            self._staged_cache.touch(cache_key)
        merged = capacity = None
        if staged is None:
            with _timed("read_columns"):
                cols, n = read_columns(
                    table,
                    sorted(base_cols),
                    m.source_op.start_time,
                    m.source_op.stop_time,
                )
            if key_plan.host_gids is not None and len(key_plan.host_gids) != n:
                return None  # table moved under us; fall back
            if flags.streaming_stage:
                # Streamed double-buffered staging: host pack ∥ HBM
                # transfer ∥ device fold per window. The aggregate is
                # computed as a side effect of staging, and the window
                # blocks concatenate into the warm-path cache entry.
                with _timed("aux"):
                    aux = self._build_aux(
                        evaluator, m, key_plan, table, device_specs
                    )
                with _timed("stage"):
                    stream = self._stream_execute(
                        m, device_specs, evaluator, key_plan, table, cols,
                        n, f32_cols, cell_cols, aux, cacheable,
                        base_row=version[0],
                    )
                if stream is not None:
                    merged, capacity, staged = stream
                    if cacheable and staged is not None:
                        self._staged_insert(
                            cache_key, staged, m.source_op.table_name, version
                        )
            if merged is None:
                int_dicts = {}
                with _timed("int_dict_encode"):
                    from pixie_tpu.parallel.staging import int_dict_encode

                    for col, max_card in cell_cols.items():
                        enc = int_dict_encode(cols[col], max_card)
                        if enc is not None:
                            cols[col], int_dicts[col] = enc
                try:
                    with _timed("stage"):
                        staged = self._stage(
                            cols, n, key_plan, table, f32_cols, int_dicts
                        )
                except Exception as e:
                    if "RESOURCE_EXHAUSTED" not in str(e) and (
                        "Out of memory" not in str(e)
                    ):
                        raise  # deterministic failures must not nuke the cache
                    # Device OOM: drop every cached staging and retry once —
                    # better than falling back to the host engine for a
                    # gigarow table. (Entries pinned by concurrent folds
                    # survive as accounted zombies; their memory was never
                    # ours to free.)
                    self._staged_cache.clear(reason="oom")
                    staged = None
                if staged is None:
                    # Retry OUTSIDE the except block: the in-flight exception's
                    # traceback pins the failed attempt's partially allocated
                    # device buffers until the handler exits.
                    with _timed("stage"):
                        staged = self._stage(
                            cols, n, key_plan, table, f32_cols, int_dicts
                        )
                if cacheable:
                    self._staged_insert(
                        cache_key, staged, m.source_op.table_name, version
                    )
        # Query-scoped pin (r12): from here until finalize returns, this
        # query's staged entry cannot be evicted underneath its fold —
        # not by a concurrent query's byte-watermark eviction, not by a
        # version bump, not by the OOM clear. Pinning a key absent from
        # the pool (non-cacheable staging) is a no-op.
        with self._staged_cache.pin(cache_key if cacheable else None):
            if merged is None:
                with _timed("aux"):
                    aux = self._build_aux(
                        evaluator, m, key_plan, table, device_specs
                    )
                with _timed("program"):
                    if flags.shared_scans:
                        # Shared scan (r12): coalesce with any concurrent
                        # query whose fold signature + aux values match —
                        # one device dispatch, per-query finalize below.
                        merged, capacity = self._shared_scan_run(
                            m, device_specs, evaluator, key_plan, staged,
                            aux, cache_key,
                        )
                    else:
                        merged, capacity = self._run_program(
                            m, device_specs, evaluator, key_plan, staged, aux
                        )
            elif flags.shared_scans and trace.ACTIVE:
                # The stream path computed the fold during staging: no
                # dispatch to share, but keep the span family uniform.
                trace.record(
                    "serving.shared_scan",
                    0,
                    attrs={"shared_scan_batch_size": 1, "role": "stream"},
                )
            if (
                self.fold_signature_store is not None
                and staged is not None
                and not windowed
            ):
                self._record_fold_shape(
                    m, device_specs, key_plan, staged, capacity, aux
                )
            if m.agg_op.stage == AggStage.PARTIAL:
                batch = self._partial_state_batch(
                    m, device_specs, key_plan, merged, table
                )
            elif windowed:
                # One RowBatch per window, eow-cadenced like the host
                # AggNode.
                batch = [
                    self._finalize(
                        m,
                        specs,
                        key_plan,
                        capacity,
                        merged,
                        registry,
                        table,
                        host_any=host_any,
                        group_range=(w * base_groups, base_groups),
                        eow=True,
                        eos=(w == n_windows - 1),
                    )
                    for w in range(n_windows)
                ]
            else:
                batch = self._finalize(
                    m,
                    specs,
                    key_plan,
                    capacity,
                    merged,
                    registry,
                    table,
                    host_any=host_any,
                )
            return m.agg_nid, batch

    # -- device join-aggregate (inner join fused into the agg) ---------------
    def _try_execute_join_agg(
        self, fragment, relations, table_store, registry, func_ctx
    ) -> Optional[tuple[int, RowBatch]]:
        m = match_join_agg(fragment, relations)
        if m is None:
            return None
        lt = table_store.get_table(m.left_source_op.table_name)
        rt = table_store.get_table(m.right_source_op.table_name)
        if lt is None or rt is None:
            return None
        # v1 gates: bare-column join keys; non-string agg args.
        if not all(isinstance(e, ColumnRef) for e in m.left_key_exprs):
            return None
        if not all(isinstance(e, ColumnRef) for e in m.right_key_exprs):
            return None
        for (_, agg), (_o, side, arg_e, _name) in zip(m.agg_op.values, m.specs):
            if len(agg.args) != 1:
                return None  # single-arg decompositions only
            rel = m.left_relation if side == 0 else m.right_relation
            try:
                if expr_data_type(arg_e, rel, registry) == DataType.STRING:
                    return None
            except (KeyError, ValueError):
                return None

        # --- shared join-key id space (host; the 'dense gids' the sorted
        # merge would use — here they index the per-key stat tensors) ------
        def read_keys(table, rel, key_exprs, src_op):
            cols, n = read_columns(
                table,
                sorted({e.name for e in key_exprs}),
                src_op.start_time,
                src_op.stop_time,
            )
            return cols, n

        lcols, nl = read_keys(lt, m.left_relation, m.left_key_exprs, m.left_source_op)
        rcols, nr = read_keys(rt, m.right_relation, m.right_key_exprs, m.right_source_op)
        lkey_arrays, rkey_arrays = [], []
        for le, re_ in zip(m.left_key_exprs, m.right_key_exprs):
            la, ra = lcols[le.name], rcols[re_.name]
            lt_dt = m.left_relation.col(le.name).data_type
            rt_dt = m.right_relation.col(re_.name).data_type
            if lt_dt == DataType.STRING or rt_dt == DataType.STRING:
                if lt_dt != rt_dt:
                    return None
                shared = StringDictionary()
                dl, dr = lt.dictionaries.get(le.name), rt.dictionaries.get(re_.name)
                if dl is None or dr is None:
                    return None
                lut_l = shared.encode(np.asarray(list(dl.values()), dtype=object))
                lut_r = shared.encode(np.asarray(list(dr.values()), dtype=object))
                la = lut_l[la] if len(lut_l) else la
                ra = lut_r[ra] if len(lut_r) else ra
            lkey_arrays.append(np.asarray(la))
            rkey_arrays.append(np.asarray(ra))
        enc = GroupEncoder()
        kl = enc.encode(lkey_arrays) if nl else np.empty(0, np.int32)
        kr = enc.encode(rkey_arrays) if nr else np.empty(0, np.int32)
        K = max(enc.num_groups, 1)
        if K > (1 << 22):
            return None  # stat tensors would be unreasonable

        # --- group-key plan over the LEFT side ---------------------------
        shim = _Match(
            source_nid=m.left_source_nid,
            agg_nid=m.agg_nid,
            source_op=m.left_source_op,
            agg_op=dataclasses.replace(
                m.agg_op, groups=tuple(g for g, _ in m.group_exprs)
            ),
            col_exprs={g: e for g, e in m.group_exprs},
            predicates=[],
            source_relation=m.left_relation,
        )
        base_left = {e.name for e in m.left_key_exprs}
        for p in m.left_preds + m.post_left_preds:
            base_left |= referenced_columns(p)
        for _, side, arg_e, _n in m.specs:
            if side == 0:
                base_left |= referenced_columns(arg_e)
        key_plan = self._plan_keys(shim, lt, registry, func_ctx, base_left)
        if key_plan is None:
            return None
        if m.group_exprs and key_plan.host_gids is None:
            # _plan_keys prefers device key paths (dict codes / LUT); the
            # join-agg program wants host gids — derive them cheaply from
            # the same dictionary structures.
            if isinstance(key_plan.device_expr, ColumnRef):
                cols2, n2 = read_columns(
                    lt,
                    [key_plan.device_expr.name],
                    m.left_source_op.start_time,
                    m.left_source_op.stop_time,
                )
                gids2 = cols2[key_plan.device_expr.name].astype(np.int32)
            elif isinstance(key_plan.device_expr, tuple):
                _, src_col, lut_codes = key_plan.device_expr
                cols2, n2 = read_columns(
                    lt,
                    [src_col],
                    m.left_source_op.start_time,
                    m.left_source_op.stop_time,
                )
                codes = np.maximum(cols2[src_col], 0)
                gids2 = np.asarray(lut_codes)[codes].astype(np.int32)
            else:
                return None
            key_plan = dataclasses.replace(key_plan, host_gids=gids2)
        if key_plan.host_gids is not None and len(key_plan.host_gids) != nl:
            return None
        if key_plan.host_gids is None:
            # Group-by-none: one global group; the program still wants a
            # staged gid lane.
            key_plan = dataclasses.replace(
                key_plan, host_gids=np.zeros(nl, np.int32), num_groups=1
            )
        capacity = _pow2_at_least(max(key_plan.num_groups, 1))
        if capacity > (1 << 20):
            return None

        # --- right-side per-key statistics (device, stays resident) ------
        base_right = set()
        for p in m.right_preds + m.post_right_preds:
            base_right |= referenced_columns(p)
        right_specs = [
            (out, arg_e, name)
            for out, side, arg_e, name in m.specs
            if side == 1
        ]
        for _, arg_e, _n in right_specs:
            base_right |= referenced_columns(arg_e)
        r_named = [
            (f"pred{i}", p)
            for i, p in enumerate(m.right_preds + m.post_right_preds)
        ] + [(f"arg:{o}", e) for o, e, _n in right_specs]
        try:
            r_eval = ExpressionEvaluator(
                r_named, m.right_relation, registry, func_ctx
            )
            l_eval = ExpressionEvaluator(
                [
                    (f"pred{i}", p)
                    for i, p in enumerate(m.left_preds + m.post_left_preds)
                ]
                + [
                    (f"arg:{o}", e)
                    for o, side, e, _n in m.specs
                    if side == 0
                ],
                m.left_relation,
                registry,
                func_ctx,
            )
        except ValueError:
            return None
        # The shared-encoder id space depends on the LEFT side too (left
        # keys are encoded first, so left content changes permute ids):
        # the right staging's identity must pin the whole key space.
        key_space_sig = (
            m.left_source_op.table_name,
            (lt.min_row_id(), lt.end_row_id()),
            repr(m.left_key_exprs) + repr(m.right_key_exprs),
            m.left_source_op.start_time,
            m.left_source_op.stop_time,
        )
        rstats = self._run_right_stats(
            m, rt, rcols_needed=sorted(base_right), kr=kr, nr=nr, K=K,
            evaluator=r_eval, right_specs=right_specs,
            key_space_sig=key_space_sig,
        )
        if rstats is None:
            return None
        # --- left-side weighted aggregation --------------------------------
        left_stage_cols = set()
        for p in m.left_preds + m.post_left_preds:
            left_stage_cols |= referenced_columns(p)
        for _o, side, e, _n in m.specs:
            if side == 0:
                left_stage_cols |= referenced_columns(e)
        out = self._run_left_join_agg(
            m, lt, sorted(left_stage_cols),
            kl, nl, key_plan, capacity, l_eval, rstats, registry,
        )
        if out is None:
            return None
        return m.agg_nid, out

    def _run_right_stats(
        self, m, table, rcols_needed, kr, nr, K, evaluator, right_specs,
        key_space_sig=None, **_
    ):
        """Stage the right side and reduce per-key stats on the mesh:
        nR[K] plus per-right-arg sum/min/max as needed. Outputs are device
        arrays (replicated); nothing is fetched."""
        cache_key = (
            m.right_source_op.table_name,
            (table.min_row_id(), table.end_row_id()),
            tuple(sorted(set(rcols_needed))),
            m.right_source_op.start_time,
            m.right_source_op.stop_time,
            self.block_rows,
            ":joinright:" + repr(key_space_sig),
            K,
            (),
        )
        staged = self._stage_cached(
            cache_key,
            table,
            m.right_source_op,
            rcols_needed,
            _KeyPlan(host_gids=kr.astype(np.int32), num_groups=K),
        )
        if staged is None or staged.num_rows != nr:
            return None
        aux = {}
        for name, e in evaluator.named_exprs:
            aux.update(evaluator.build_aux(e, table.dictionaries))
        col_names = sorted(staged.blocks)
        narrow_names = sorted(staged.narrow_offsets)
        preds = [e for n, e in evaluator.named_exprs if n.startswith("pred")]
        axis = self.mesh_axes  # collectives reduce over the FULL mesh
        ndev = staged.num_devices
        aux_order = list(aux.keys())
        stat_kinds = []  # [(spec out name, kind)] kinds: sum/min/max
        for out, _e, name in right_specs:
            if name in ("sum", "mean"):
                stat_kinds.append((out, "sum"))
            elif name == "min":
                stat_kinds.append((out, "min"))
            elif name == "max":
                stat_kinds.append((out, "max"))
            else:
                return None  # count needs no right stat

        sig = "|".join(
            [
                "joinR",
                ",".join(
                    f"{n2}:{a.shape}:{a.dtype}"
                    for n2, a in sorted(staged.blocks.items())
                ),
                f"narrow:{narrow_names}",
                f"K:{K}",
                "preds:" + ";".join(repr(p) for p in preds),
                "stats:" + ";".join(f"{o}:{k}" for o, k in stat_kinds),
                "aux:" + ",".join(
                    f"{np.shape(v)}:{np.asarray(v).dtype}" for v in aux.values()
                ),
                f"mesh:{self._mesh_sig}",
            ]
        )
        arg_exprs = {o: e for o, e, _n in right_specs}

        if sig not in self._program_cache:

            def shard_fn(*arrs):
                i = len(col_names)
                cols = {n: a[0] for n, a in zip(col_names, arrs[:i])}
                mask_all = arrs[i][0]
                jk_all = arrs[i + 1][0]
                i += 2
                end = len(arrs)
                narrow_vec = None
                if narrow_names:
                    narrow_vec = arrs[-1]
                    end -= 1
                aux_v = dict(zip(aux_order, arrs[i:end]))

                def body(carry, xs):
                    from pixie_tpu.ops import segment as _segment

                    counts, sums, mins, maxs = carry
                    blk_cols, blk_mask, blk_jk = xs
                    env = dict(zip(col_names, blk_cols))
                    for ni, nm in enumerate(narrow_names):
                        env[nm] = env[nm].astype(jnp.int64) + narrow_vec[ni]
                    mask = blk_mask
                    for p in preds:
                        mask = mask & evaluator.device_eval(p, env, aux_v)
                    jk = blk_jk.astype(jnp.int32)
                    counts = counts + _segment.seg_sum(
                        mask.astype(jnp.float64), jk, K
                    )
                    new_sums = {}
                    for o, kind in stat_kinds:
                        val = evaluator.device_eval(
                            arg_exprs[o], env, aux_v
                        ).astype(jnp.float64)
                        if kind == "sum":
                            new_sums[o] = sums[o] + _segment.seg_sum(
                                val, jk, K, mask
                            )
                        elif kind == "min":
                            mins[o] = jnp.minimum(
                                mins[o],
                                _segment.seg_min(val, jk, K, mask),
                            )
                        else:
                            maxs[o] = jnp.maximum(
                                maxs[o],
                                _segment.seg_max(val, jk, K, mask),
                            )
                    sums.update(new_sums)
                    return (counts, sums, mins, maxs), None

                init = (
                    jnp.zeros(K, jnp.float64),
                    {o: jnp.zeros(K, jnp.float64) for o, k in stat_kinds if k == "sum"},
                    {o: jnp.full(K, jnp.inf) for o, k in stat_kinds if k == "min"},
                    {o: jnp.full(K, -jnp.inf) for o, k in stat_kinds if k == "max"},
                )
                xs = (
                    tuple(cols[n] for n in col_names),
                    mask_all,
                    jk_all,
                )
                (counts, sums, mins, maxs), _ = jax.lax.scan(body, init, xs)
                if ndev > 1:
                    counts = jax.lax.psum(counts, axis)
                    sums = {o: jax.lax.psum(v, axis) for o, v in sums.items()}
                    mins = {o: jax.lax.pmin(v, axis) for o, v in mins.items()}
                    maxs = {o: jax.lax.pmax(v, axis) for o, v in maxs.items()}
                return (
                    (counts,)
                    + tuple(sums[o] for o, k in stat_kinds if k == "sum")
                    + tuple(mins[o] for o, k in stat_kinds if k == "min")
                    + tuple(maxs[o] for o, k in stat_kinds if k == "max")
                )

            n_sharded = len(col_names) + 2
            n_repl = len(aux_order) + (1 if narrow_names else 0)
            in_specs = tuple([P(axis)] * n_sharded + [P()] * n_repl)
            n_out = 1 + len(stat_kinds)
            program = jax.jit(
                shard_map(
                    shard_fn,
                    mesh=self.mesh,
                    in_specs=in_specs,
                    out_specs=tuple([P()] * n_out),
                    **_SM_CHECK_KW,
                )
            )
            self._program_cache[sig] = (program, len(aux_order), None)
            _PROGRAMS.set(len(self._program_cache))
        program = self._program_cache[sig][0]
        args = [staged.blocks[n2] for n2 in col_names]
        args.append(staged.mask)
        args.append(staged.gids)  # join-key ids staged as gids
        args.extend(jnp.asarray(v) for v in aux.values())
        if staged.narrow_offsets:
            args.append(
                jnp.asarray(
                    [staged.narrow_offsets[n2] for n2 in narrow_names],
                    jnp.int64,
                )
            )
        from pixie_tpu.ops import segment as _segment

        with _segment.platform_hint(self.mesh.devices.flat[0].platform):
            outs = program(*args)
        result = {"__n__": outs[0]}
        idx = 1
        for o, k in [(o, k) for o, k in stat_kinds if k == "sum"]:
            result[f"sum:{o}"] = outs[idx]
            idx += 1
        for o, k in [(o, k) for o, k in stat_kinds if k == "min"]:
            result[f"min:{o}"] = outs[idx]
            idx += 1
        for o, k in [(o, k) for o, k in stat_kinds if k == "max"]:
            result[f"max:{o}"] = outs[idx]
            idx += 1
        return result

    def _run_left_join_agg(
        self, m, table, lcols_needed, kl, nl, key_plan, capacity,
        evaluator, rstats, registry,
    ):
        """Scan the LEFT side with per-row join weights gathered from the
        right-key stats; segment-reduce per agg group; fetch one buffer."""
        from pixie_tpu.types.dtypes import host_dtype

        base = set(lcols_needed)
        cache_key = (
            m.left_source_op.table_name,
            (table.min_row_id(), table.end_row_id()),
            tuple(sorted(base)),
            m.left_source_op.start_time,
            m.left_source_op.stop_time,
            self.block_rows,
            ":joinleft:" + repr(m.left_key_exprs) + repr(
                [e for _, e in m.group_exprs]
            ),
            key_plan.num_groups,
            (),
        )
        staged = self._stage_cached(
            cache_key,
            table,
            m.left_source_op,
            base,
            key_plan,
            extra_cols={"__jk__": kl.astype(np.int32)},
        )
        if staged is None or staged.num_rows != nl:
            return None
        aux = {}
        for name, e in evaluator.named_exprs:
            aux.update(evaluator.build_aux(e, table.dictionaries))
        col_names = sorted(staged.blocks)
        narrow_names = sorted(staged.narrow_offsets)
        preds = [e for n, e in evaluator.named_exprs if n.startswith("pred")]
        axis = self.mesh_axes  # collectives reduce over the FULL mesh
        ndev = staged.num_devices
        aux_order = list(aux.keys())
        stat_names = sorted(rstats)
        arg_exprs = {
            o: e for o, side, e, _n in m.specs if side == 0
        }
        spec_plan = [(o, side, name) for o, side, _e, name in m.specs]

        sig = "|".join(
            [
                "joinL",
                ",".join(
                    f"{n2}:{a.shape}:{a.dtype}"
                    for n2, a in sorted(staged.blocks.items())
                ),
                f"narrow:{narrow_names}",
                f"cap:{capacity}",
                "preds:" + ";".join(repr(p) for p in preds),
                "specs:" + ";".join(
                    f"{o}:{s}:{n2}" for o, s, n2 in spec_plan
                ),
                "largs:" + ";".join(
                    f"{o}={e!r}" for o, e in sorted(arg_exprs.items())
                ),
                "stats:" + ",".join(stat_names),
                "aux:" + ",".join(
                    f"{np.shape(v)}:{np.asarray(v).dtype}" for v in aux.values()
                ),
                f"mesh:{self._mesh_sig}",
            ]
        )
        if sig not in self._program_cache:

            def shard_fn(*arrs):
                from pixie_tpu.ops import segment as _segment

                i = len(col_names)
                cols = {n: a[0] for n, a in zip(col_names, arrs[:i])}
                mask_all = arrs[i][0]
                gids_all = arrs[i + 1][0]
                i += 2
                stats = dict(zip(stat_names, arrs[i : i + len(stat_names)]))
                i += len(stat_names)
                end = len(arrs)
                narrow_vec = None
                if narrow_names:
                    narrow_vec = arrs[-1]
                    end -= 1
                aux_v = dict(zip(aux_order, arrs[i:end]))
                nR = stats["__n__"]

                def body(carry, xs):
                    acc = carry
                    blk_cols, blk_mask, blk_gids = xs
                    env = dict(zip(col_names, blk_cols))
                    for ni, nm in enumerate(narrow_names):
                        env[nm] = env[nm].astype(jnp.int64) + narrow_vec[ni]
                    mask = blk_mask
                    for p in preds:
                        mask = mask & evaluator.device_eval(p, env, aux_v)
                    jk = env["__jk__"].astype(jnp.int32)
                    w = nR[jk]
                    mask = mask & (w > 0)
                    gids = blk_gids.astype(jnp.int32)
                    wm = jnp.where(mask, w, 0.0)
                    new_acc = dict(acc)
                    new_acc["__count__"] = acc["__count__"] + _segment.seg_sum(
                        wm, gids, capacity
                    )
                    for o, side, name in spec_plan:
                        key = f"s:{o}"
                        if name == "count":
                            continue  # __count__ serves every count spec
                        if side == 0:
                            val = evaluator.device_eval(
                                arg_exprs[o], env, aux_v
                            ).astype(jnp.float64)
                            if name in ("sum", "mean"):
                                new_acc[key] = acc[key] + _segment.seg_sum(
                                    val * wm, gids, capacity
                                )
                            elif name == "min":
                                new_acc[key] = jnp.minimum(
                                    acc[key],
                                    _segment.seg_min(val, gids, capacity, mask),
                                )
                            else:
                                new_acc[key] = jnp.maximum(
                                    acc[key],
                                    _segment.seg_max(val, gids, capacity, mask),
                                )
                        else:
                            if name in ("sum", "mean"):
                                g = stats[f"sum:{o}"][jk]
                                new_acc[key] = acc[key] + _segment.seg_sum(
                                    jnp.where(mask, g, 0.0), gids, capacity
                                )
                            elif name == "min":
                                g = stats[f"min:{o}"][jk]
                                new_acc[key] = jnp.minimum(
                                    acc[key],
                                    _segment.seg_min(g, gids, capacity, mask),
                                )
                            else:
                                g = stats[f"max:{o}"][jk]
                                new_acc[key] = jnp.maximum(
                                    acc[key],
                                    _segment.seg_max(g, gids, capacity, mask),
                                )
                    return new_acc, None

                init = {"__count__": jnp.zeros(capacity, jnp.float64)}
                for o, side, name in spec_plan:
                    if name == "count":
                        continue
                    if name in ("sum", "mean"):
                        init[f"s:{o}"] = jnp.zeros(capacity, jnp.float64)
                    elif name == "min":
                        init[f"s:{o}"] = jnp.full(capacity, jnp.inf)
                    else:
                        init[f"s:{o}"] = jnp.full(capacity, -jnp.inf)
                xs = (
                    tuple(cols[n] for n in col_names),
                    mask_all,
                    gids_all,
                )
                acc, _ = jax.lax.scan(body, init, xs)
                if ndev > 1:
                    merged = {}
                    merged["__count__"] = jax.lax.psum(acc["__count__"], axis)
                    for o, side, name in spec_plan:
                        if name == "count":
                            continue
                        k2 = f"s:{o}"
                        if name in ("sum", "mean"):
                            merged[k2] = jax.lax.psum(acc[k2], axis)
                        elif name == "min":
                            merged[k2] = jax.lax.pmin(acc[k2], axis)
                        else:
                            merged[k2] = jax.lax.pmax(acc[k2], axis)
                    acc = merged
                parts = [acc["__count__"]]
                for o, side, name in spec_plan:
                    if name != "count":
                        parts.append(acc[f"s:{o}"])
                return jnp.concatenate(parts)

            n_sharded = len(col_names) + 2
            n_repl = (
                len(stat_names)
                + len(aux_order)
                + (1 if narrow_names else 0)
            )
            in_specs = tuple([P(axis)] * n_sharded + [P()] * n_repl)
            program = jax.jit(
                shard_map(
                    shard_fn,
                    mesh=self.mesh,
                    in_specs=in_specs,
                    out_specs=P(),
                    **_SM_CHECK_KW,
                )
            )
            self._program_cache[sig] = (program, len(aux_order), None)
            _PROGRAMS.set(len(self._program_cache))
        program = self._program_cache[sig][0]
        args = [staged.blocks[n2] for n2 in col_names]
        args.append(staged.mask)
        args.append(staged.gids)
        args.extend(rstats[n2] for n2 in stat_names)
        args.extend(jnp.asarray(v) for v in aux.values())
        if staged.narrow_offsets:
            args.append(
                jnp.asarray(
                    [staged.narrow_offsets[n2] for n2 in narrow_names],
                    jnp.int64,
                )
            )
        from pixie_tpu.ops import segment as _segment

        with _segment.platform_hint(self.mesh.devices.flat[0].platform):
            buf = np.asarray(program(*args))
        counts = buf[:capacity]
        vals = {}
        off = capacity
        for o, side, name in spec_plan:
            if name != "count":
                vals[o] = buf[off : off + capacity]
                off += capacity
        n = max(key_plan.num_groups, 1) if m.agg_op.groups else 1
        keep = counts[:n] > 0 if m.agg_op.groups else np.ones(1, bool)
        rel = m.agg_op.output_relation(
            [self._join_pre_agg_relation(m, registry)], registry
        )
        out_cols: list = []
        for (g, _e), col in zip(m.group_exprs, key_plan.key_columns):
            out_cols.append(
                col.take(np.nonzero(keep)[0])
                if isinstance(col, DictColumn)
                else np.asarray(col)[keep]
            )
        for out_name, side, _e, name in m.specs:
            schema = rel.col(out_name)
            if name == "count":
                out = counts[:n][keep]
            elif name == "mean":
                out = vals[out_name][:n][keep] / np.maximum(
                    counts[:n][keep], 1.0
                )
            else:
                out = vals[out_name][:n][keep]
            dt = host_dtype(schema.data_type)
            if np.issubdtype(dt, np.integer):
                out = np.round(out).astype(dt)
            else:
                out = out.astype(dt)
            out_cols.append(out)
        return RowBatch(rel, out_cols, eow=True, eos=True)

    def _join_pre_agg_relation(self, m: "_JoinAggMatch", registry):
        """Relation the agg's output resolution expects: group columns (in
        left-source terms) + the post-join arg columns typed per side."""
        from pixie_tpu.types import ColumnSchema, Relation as _Relation

        cols = []
        seen = set()
        for g, e in m.group_exprs:
            cols.append(
                ColumnSchema(
                    g, expr_data_type(e, m.left_relation, registry)
                )
            )
            seen.add(g)
        # Arg columns: the AggOp's value exprs reference post-join names;
        # synthesize a relation typing each referenced column by its side.
        for out_name, agg in m.agg_op.values:
            for ref in referenced_columns(agg):
                if ref in seen:
                    continue
                for _o, side, arg_e, _n in m.specs:
                    if _o == out_name:
                        rel = (
                            m.left_relation if side == 0 else m.right_relation
                        )
                        try:
                            dt = expr_data_type(arg_e, rel, registry)
                        except (KeyError, ValueError):
                            dt = DataType.FLOAT64
                        cols.append(ColumnSchema(ref, dt))
                        seen.add(ref)
                        break
        return _Relation(cols)

    # -- device sort-merge join (r19) ----------------------------------------
    def _try_execute_join(
        self, fragment, relations, table_store, registry, func_ctx
    ) -> Optional[tuple[int, RowBatch]]:
        """Standalone equijoin on the mesh (r19): both sides stage under
        the fold path's geometry (ResidencyPool byte accounting, r13 codec
        on the wire, join-key ids riding the gids lane), the device orders
        the build side with ONE stable packed-key sort — reproducing the
        host EquijoinNode's per-key original row order — merges via
        searchsorted, and gathers match pairs plus compacted unmatched
        rows for the outer variants into statically-capped outputs
        (exact match/unmatched counts come from host bincounts, padded to
        a power of two). Bit-identical to the host JoinNode across all
        four join types; whatever follows the join runs on the host
        against the spliced batch. Returns None on any unsupported shape
        — offload is an optimization, never a correctness cliff."""
        if not flags.device_join:
            return None
        m = match_join(fragment, relations)
        if m is None:
            return None
        lt = table_store.get_table(m.left_source_op.table_name)
        rt = table_store.get_table(m.right_source_op.table_name)
        if lt is None or rt is None:
            return None
        # v1 gates: bare-column keys and outputs. r20 lifts the pre-join
        # predicate refusal: single-table conjunctive predicates from the
        # script suffix lower through the r16 normalizer (the digest pins
        # the staging identity) and filter each side ON THE HOST before
        # staging — boolean-mask selection preserves original row order,
        # so the device merge sees exactly the rows the host engine's
        # pre-join FilterNode keeps, in the same order, and INNER/LEFT
        # row-order bit-identity carries over unchanged. A predicate
        # outside the normalizable class still refuses to the host.
        lpred_digest = rpred_digest = ""
        if m.left_preds:
            lpred_digest = predicate_fold_digest(
                m.left_preds, m.left_relation, registry, func_ctx
            )
            if lpred_digest is None:
                return None
        if m.right_preds:
            rpred_digest = predicate_fold_digest(
                m.right_preds, m.right_relation, registry, func_ctx
            )
            if rpred_digest is None:
                return None
        if not all(
            isinstance(e, ColumnRef)
            for e in m.left_key_exprs + m.right_key_exprs
        ):
            return None
        out_plan = []  # [(side, source col, out name, DataType)]
        for side, in_col, out_name in m.join_op.output_columns:
            src_map = m.left_exprs if side == 0 else m.right_exprs
            e = substitute(ColumnRef(in_col), src_map)
            if not isinstance(e, ColumnRef):
                return None
            dt = m.out_relation.col(out_name).data_type
            if dt == DataType.STRING and (
                (lt if side == 0 else rt).dictionaries.get(e.name) is None
            ):
                return None
            out_plan.append((side, e.name, out_name, dt))
        lneed = {e.name for e in m.left_key_exprs}
        for p in m.left_preds:
            lneed |= referenced_columns(p)
        rneed = {e.name for e in m.right_key_exprs}
        for p in m.right_preds:
            rneed |= referenced_columns(p)
        lcols, nl = read_columns(
            lt,
            sorted(lneed),
            m.left_source_op.start_time,
            m.left_source_op.stop_time,
        )
        rcols, nr = read_columns(
            rt,
            sorted(rneed),
            m.right_source_op.start_time,
            m.right_source_op.stop_time,
        )
        # Host-evaluate each side's predicate mask over the same read the
        # keys came from (one snapshot), then filter keys before encoding;
        # the mask rides into staging as ``row_sel``.
        left_sel = right_sel = None
        if m.left_preds:
            left_sel = self._host_pred_mask(
                m.left_preds, m.left_relation, lt, lcols, registry,
                func_ctx,
            )
            if left_sel is None or len(left_sel) != nl:
                return None
            lcols = {c: np.asarray(a)[left_sel] for c, a in lcols.items()}
            nl = int(np.count_nonzero(left_sel))
        if m.right_preds:
            right_sel = self._host_pred_mask(
                m.right_preds, m.right_relation, rt, rcols, registry,
                func_ctx,
            )
            if right_sel is None or len(right_sel) != nr:
                return None
            rcols = {c: np.asarray(a)[right_sel] for c, a in rcols.items()}
            nr = int(np.count_nonzero(right_sel))
        if nl == 0 or nr == 0:
            return None  # trivial side: the host hash join wins outright
        cm = _cost_model()
        if cm.ACTIVE:
            # r22: with measured wall times for BOTH join lanes (device
            # sort-merge vs host EquijoinNode — bit-identical outputs by
            # the r19 contract) the cost model may move the
            # device_join_min_rows gate, within rails: never device
            # below flag/rail_factor rows. Cold or shadow, the default
            # reproduces the flag comparison exactly.
            if not cm.choose_device_join(
                nl + nr, nl + nr >= int(flags.device_join_min_rows)
            ):
                return None
        elif nl + nr < flags.device_join_min_rows:
            return None
        _join_t0 = time.perf_counter()
        # Shared join-key id space over BOTH sides (the join-agg idiom):
        # string keys align through one StringDictionary, then a
        # GroupEncoder densifies; right-only keys get ids the left never
        # uses, so they match nothing.
        lkey_arrays, rkey_arrays = [], []
        for le, re_ in zip(m.left_key_exprs, m.right_key_exprs):
            la, ra = lcols[le.name], rcols[re_.name]
            lt_dt = m.left_relation.col(le.name).data_type
            rt_dt = m.right_relation.col(re_.name).data_type
            if lt_dt == DataType.STRING or rt_dt == DataType.STRING:
                if lt_dt != rt_dt:
                    return None
                shared = StringDictionary()
                dl = lt.dictionaries.get(le.name)
                dr = rt.dictionaries.get(re_.name)
                if dl is None or dr is None:
                    return None
                lut_l = shared.encode(
                    np.asarray(list(dl.values()), dtype=object)
                )
                lut_r = shared.encode(
                    np.asarray(list(dr.values()), dtype=object)
                )
                la = lut_l[la] if len(lut_l) else la
                ra = lut_r[ra] if len(lut_r) else ra
            lkey_arrays.append(np.asarray(la))
            rkey_arrays.append(np.asarray(ra))
        enc = GroupEncoder()
        kl = enc.encode(lkey_arrays)
        kr = enc.encode(rkey_arrays)
        K = max(enc.num_groups, 1)
        if K > (1 << 22):
            return None
        # Exact output cardinalities from host bincounts — they size the
        # static gather caps AND the host-side result slices.
        count_l = np.bincount(kl, minlength=K).astype(np.int64)
        count_r = np.bincount(kr, minlength=K).astype(np.int64)
        how = m.join_op.how
        M = int((count_l * count_r).sum())
        UR = (
            int(count_r[count_l == 0].sum())
            if how in (JoinType.RIGHT, JoinType.OUTER)
            else 0
        )
        UL = (
            int(count_l[count_r == 0].sum())
            if how in (JoinType.LEFT, JoinType.OUTER)
            else 0
        )
        if M + UR + UL > flags.device_join_max_out:
            return None
        cap_m = _pow2_at_least(max(M, 1))
        cap_r = _pow2_at_least(max(UR, 1)) if UR or (
            how in (JoinType.RIGHT, JoinType.OUTER)
        ) else 0
        cap_l = _pow2_at_least(max(UL, 1)) if UL or (
            how in (JoinType.LEFT, JoinType.OUTER)
        ) else 0
        # Fault site: poison the device join dispatch (chaos tests prove
        # the r9 breaker trips and the host JoinNode result is identical).
        if faults.ACTIVE:
            faults.check("device.join_dispatch")
        # Both stagings' identity must pin the WHOLE key space: left keys
        # encode first, so either side's content changes both sides' ids
        # (the r4 ":joinright:" precedent).
        key_space_sig = (
            m.left_source_op.table_name,
            (lt.min_row_id(), lt.end_row_id()),
            m.right_source_op.table_name,
            (rt.min_row_id(), rt.end_row_id()),
            repr(m.left_key_exprs) + repr(m.right_key_exprs),
            m.left_source_op.start_time,
            m.left_source_op.stop_time,
            m.right_source_op.start_time,
            m.right_source_op.stop_time,
            lpred_digest,
            rpred_digest,
        )
        # A side with no output columns still needs mask+gids lanes on
        # device; stage its (cheap, already-read) first key column.
        cols_l = sorted(
            {src for side, src, _o, _dt in out_plan if side == 0}
            or {m.left_key_exprs[0].name}
        )
        cols_r = sorted(
            {src for side, src, _o, _dt in out_plan if side == 1}
            or {m.right_key_exprs[0].name}
        )
        # r21 distributed sort-merge (tentpole): on a multi-axis mesh,
        # range-partition both sides by packed key across the hosts
        # axis and sort+merge locally per shard, instead of replicating
        # the whole key space onto every device. Any refusal falls
        # through to the replicated v1 path below — never to the host.
        if (
            flags.mesh_distributed_join
            and len(self.mesh_axes) > 1
            and int(self.mesh.devices.shape[0]) > 1
        ):
            out = self._try_partitioned_join(
                m, lt, rt, kl, kr, K, count_l, count_r, how,
                out_plan, key_space_sig, cols_l, cols_r,
                left_sel, right_sel, nl, nr,
            )
            if out is not None:
                if cm.ACTIVE:
                    cm.observe_family(
                        "join|joinlane:sort_merge",
                        nl + nr,
                        time.perf_counter() - _join_t0,
                    )
                return m.join_nid, out
        ck_l = (
            m.left_source_op.table_name,
            (lt.min_row_id(), lt.end_row_id()),
            tuple(cols_l),
            m.left_source_op.start_time,
            m.left_source_op.stop_time,
            self.block_rows,
            ":joindevL:" + repr(key_space_sig),
            K,
            (),
        )
        ck_r = (
            m.right_source_op.table_name,
            (rt.min_row_id(), rt.end_row_id()),
            tuple(cols_r),
            m.right_source_op.start_time,
            m.right_source_op.stop_time,
            self.block_rows,
            ":joindevR:" + repr(key_space_sig),
            K,
            (),
        )
        staged_l = self._stage_cached(
            ck_l, lt, m.left_source_op, cols_l,
            _KeyPlan(host_gids=kl.astype(np.int32), num_groups=K),
            row_sel=left_sel,
        )
        if staged_l is None or staged_l.num_rows != nl:
            return None
        staged_r = self._stage_cached(
            ck_r, rt, m.right_source_op, cols_r,
            _KeyPlan(host_gids=kr.astype(np.int32), num_groups=K),
            row_sel=right_sel,
        )
        if staged_r is None or staged_r.num_rows != nr:
            return None
        out = self._run_device_join(
            m, lt, rt, staged_l, staged_r, ck_l, ck_r, out_plan,
            M, UR, UL, cap_m, cap_r, cap_l, K,
        )
        if out is None:
            return None
        if cm.ACTIVE:
            # r22: the device lane's measured wall (encode + stage +
            # sort-merge dispatch) is the B side of the gate the cost
            # model now decides.
            cm.observe_family(
                "join|joinlane:sort_merge",
                nl + nr,
                time.perf_counter() - _join_t0,
            )
        return m.join_nid, out

    def _host_pred_mask(
        self, preds, relation, table, cols, registry, func_ctx
    ):
        """AND of pre-join predicates evaluated on the host over the
        already-read columns — the same ExpressionEvaluator the host
        FilterNode runs, so the kept-row set (and its order under
        boolean-mask selection) is bit-identical to the host plan's
        pre-join filter. None refuses: missing dictionary, column not
        read, or an unresolvable UDF sends the join back to the host
        engine."""
        from pixie_tpu.types import Relation as _Relation

        needed = set()
        for p in preds:
            needed |= referenced_columns(p)
        if not needed:
            return None  # constant predicates: host engine's job
        schemas, batch_cols = [], []
        for name in sorted(needed):
            arr = cols.get(name)
            if arr is None:
                return None
            schema = relation.col(name)
            if schema.data_type == DataType.STRING:
                d = table.dictionaries.get(name)
                if d is None:
                    return None
                arr = DictColumn(np.asarray(arr).astype(np.int32), d)
            schemas.append(schema)
            batch_cols.append(arr)
        sub_rel = _Relation(schemas)
        batch = RowBatch(sub_rel, batch_cols)
        mask = None
        try:
            for i, p in enumerate(preds):
                ev = ExpressionEvaluator(
                    [(f"p{i}", p)], sub_rel, registry, func_ctx
                )
                m2 = ev.evaluate_predicate(batch)
                mask = m2 if mask is None else (mask & m2)
        except (ValueError, KeyError):
            return None
        return mask

    def _run_device_join(
        self, m, lt, rt, staged_l, staged_r, ck_l, ck_r, out_plan,
        M, UR, UL, cap_m, cap_r, cap_l, K,
    ):
        """Compile-or-reuse the sort-merge join program and dispatch it.
        Output layout per column is three statically-capped sections
        [matched cap_m | probe-unmatched cap_r | build-unmatched cap_l];
        the host slices the exact counts back out. Match pairs are
        probe-row-major with build rows in stable per-key original order —
        exactly the host engine's emission for a single probe batch (and
        a multiset-identical one otherwise; join row order is not a
        contract, preserves_time_order=False)."""
        from pixie_tpu.ops import segment as _segment
        from pixie_tpu.types.dtypes import host_dtype

        l_names = sorted(staged_l.blocks)
        r_names = sorted(staged_r.blocks)
        l_narrow = sorted(staged_l.narrow_offsets)
        r_narrow = sorted(staged_r.narrow_offsets)
        axis = self.mesh_axes  # collectives reduce over the FULL mesh
        ndev = staged_l.num_devices
        sig = "|".join(
            [
                "join",
                "joinlane:sort_merge",
                f"how:{m.join_op.how.value}",
                "L:" + ",".join(
                    f"{n2}:{a.shape}:{a.dtype}"
                    for n2, a in sorted(staged_l.blocks.items())
                ),
                f"lnarrow:{l_narrow}",
                "R:" + ",".join(
                    f"{n2}:{a.shape}:{a.dtype}"
                    for n2, a in sorted(staged_r.blocks.items())
                ),
                f"rnarrow:{r_narrow}",
                f"caps:{cap_m},{cap_r},{cap_l}",
                "out:" + ";".join(
                    f"{side}:{src}:{dt.name}"
                    for side, src, _o, dt in out_plan
                ),
                f"mesh:{self._mesh_sig}",
            ]
        )
        if sig not in self._program_cache:
            _segment.lane_count("join_sort_merge")

            def shard_fn(*arrs):
                i = len(l_names)
                lcols = dict(zip(l_names, arrs[:i]))
                lmask_b, lgids_b = arrs[i], arrs[i + 1]
                i += 2
                rcols = dict(zip(r_names, arrs[i : i + len(r_names)]))
                i += len(r_names)
                rmask_b, rgids_b = arrs[i], arrs[i + 1]
                k_arr = arrs[i + 2]
                i += 3
                lnarrow_vec = rnarrow_vec = None
                if l_narrow:
                    lnarrow_vec = arrs[i]
                    i += 1
                if r_narrow:
                    rnarrow_vec = arrs[i]

                def flatten(a):
                    # Per-device [1, nblk, B] → the GLOBAL row order:
                    # staging packs rows device-contiguously with all
                    # padding at the tail, so all_gather + flatten is the
                    # original cursor order. The merge itself runs
                    # replicated (a join's output is a global ordering; a
                    # distributed merge is future work — the caps gate
                    # keeps the replicated sort affordable).
                    x = a[0].reshape(-1)
                    if ndev > 1:
                        x = jax.lax.all_gather(x, axis).reshape(-1)
                    return x

                lmask = flatten(lmask_b)
                lgid = flatten(lgids_b).astype(jnp.int32)
                rmask = flatten(rmask_b)
                rgid = flatten(rgids_b).astype(jnp.int32)
                kq = k_arr.astype(jnp.int32)
                # Padded rows take per-side sentinels ABOVE every real key
                # id so they can never pair (build pads K, probe pads K+1).
                lkey = jnp.where(lmask, lgid, kq)
                rkey = jnp.where(rmask, rgid, kq + 1)
                build_rows, probe_rows, _fan, ur, ul = (
                    _segment.local_sort_merge(
                        lkey, rkey, lmask, rmask, cap_m, cap_r, cap_l
                    )
                )
                outs = []
                for side, src, _o, dt in out_plan:
                    if side == 0:
                        col = flatten(lcols[src])
                        narrow_v = (
                            lnarrow_vec[l_narrow.index(src)]
                            if src in l_narrow
                            else None
                        )
                        midx, uidx_r, uidx_l = build_rows, None, ul
                    else:
                        col = flatten(rcols[src])
                        narrow_v = (
                            rnarrow_vec[r_narrow.index(src)]
                            if src in r_narrow
                            else None
                        )
                        midx, uidx_r, uidx_l = probe_rows, ur, None
                    nside = col.shape[0]
                    odt = jnp.int64 if narrow_v is not None else col.dtype
                    # Null rows carry the host engine's type defaults:
                    # 0/False for value columns, code -1 for string
                    # columns (decoded to "" host-side).
                    nullv = -1 if dt == DataType.STRING else 0

                    def gath(idx, col=col, narrow_v=narrow_v, nside=nside):
                        g = col[jnp.clip(idx, 0, nside - 1)]
                        if narrow_v is not None:
                            g = g.astype(jnp.int64) + narrow_v
                        return g

                    secs = [gath(midx)]
                    if cap_r:
                        secs.append(
                            gath(uidx_r)
                            if uidx_r is not None
                            else jnp.full(cap_r, nullv, odt)
                        )
                    if cap_l:
                        secs.append(
                            gath(uidx_l)
                            if uidx_l is not None
                            else jnp.full(cap_l, nullv, odt)
                        )
                    outs.append(
                        jnp.concatenate(secs) if len(secs) > 1 else secs[0]
                    )
                return tuple(outs)

            n_sharded = len(l_names) + 2 + len(r_names) + 2
            n_repl = 1 + (1 if l_narrow else 0) + (1 if r_narrow else 0)
            program = jax.jit(
                shard_map(
                    shard_fn,
                    mesh=self.mesh,
                    in_specs=tuple([P(axis)] * n_sharded + [P()] * n_repl),
                    out_specs=tuple([P()] * len(out_plan)),
                    **_SM_CHECK_KW,
                )
            )
            self._program_cache[sig] = (program, 0, None)
            _PROGRAMS.set(len(self._program_cache))
        program = self._program_cache[sig][0]
        args = [staged_l.blocks[n2] for n2 in l_names]
        args.append(staged_l.mask)
        args.append(staged_l.gids)
        args += [staged_r.blocks[n2] for n2 in r_names]
        args.append(staged_r.mask)
        args.append(staged_r.gids)
        args.append(jnp.asarray(K, jnp.int32))
        if l_narrow:
            args.append(
                jnp.asarray(
                    [staged_l.narrow_offsets[n2] for n2 in l_narrow],
                    jnp.int64,
                )
            )
        if r_narrow:
            args.append(
                jnp.asarray(
                    [staged_r.narrow_offsets[n2] for n2 in r_narrow],
                    jnp.int64,
                )
            )
        # Pin BOTH staged sides for the dispatch (r12): a concurrent
        # query's watermark eviction must not drop either mid-join.
        with self._staged_cache.pin(ck_l):
            with self._staged_cache.pin(ck_r):
                with _segment.platform_hint(
                    self.mesh.devices.flat[0].platform
                ):
                    outs = program(*args)
        data = {}
        for ci, (side, src, out_name, dt) in enumerate(out_plan):
            arr = np.asarray(outs[ci])
            segs = [arr[:M]]
            off = cap_m
            if cap_r:
                segs.append(arr[off : off + UR])
                off += cap_r
            if cap_l:
                segs.append(arr[off : off + UL])
            a = np.concatenate(segs) if len(segs) > 1 else segs[0]
            if dt == DataType.STRING:
                codes = a.astype(np.int32)
                d2 = (lt if side == 0 else rt).dictionaries.get(src)
                if d2 is None:
                    return None
                if (codes < 0).any():
                    # Outer-null rows decode to "" — the host engine's
                    # type-default padding (join_node._null_batch);
                    # from_pydict re-encodes the object array.
                    vocab = np.asarray(list(d2.values()), dtype=object)
                    vals = np.empty(len(codes), dtype=object)
                    neg = codes < 0
                    vals[~neg] = vocab[codes[~neg]]
                    vals[neg] = ""
                    data[out_name] = vals
                else:
                    data[out_name] = DictColumn(codes, d2)
            else:
                data[out_name] = a.astype(host_dtype(dt))
        return RowBatch.from_pydict(
            m.out_relation, data, eow=True, eos=True
        )

    def _try_partitioned_join(
        self, m, lt, rt, kl, kr, K, count_l, count_r, how,
        out_plan, key_space_sig, cols_l, cols_r,
        left_sel, right_sel, nl, nr,
    ):
        """Distributed sort-merge join over the hosts axis (r21): both
        sides range-partition by packed key id into one contiguous key
        range per host (balanced by per-key join work from the exact
        host bincounts), stage shard-major so every host's devices hold
        only its shard, and each host sorts + merges its shard locally
        (all_gather over the INNER axes only). Shard outputs then
        concatenate over the hosts axis and the host reorders them to
        the engine's emission order — bit-identical to both the v1
        replicated lane and the host EquijoinNode. Returns the spliced
        RowBatch, or None to fall through to the v1 replicated path."""
        H = int(self.mesh.devices.shape[0])
        # Balanced contiguous key ranges: per-key cost = emitted pairs
        # plus the rows that move (both exact).
        work = count_l * count_r + count_l + count_r
        cum = np.cumsum(work)
        total_w = int(cum[-1]) if len(cum) else 0
        if total_w <= 0:
            return None
        targets = (np.arange(1, H, dtype=np.int64) * total_w) // H
        bounds = np.searchsorted(cum, targets, side="left")
        key_shard = np.searchsorted(
            bounds, np.arange(K), side="right"
        ).astype(np.int32)
        shard_l = key_shard[kl]
        shard_r = key_shard[kr]
        # Stable shard-major permutations: original row order survives
        # WITHIN each shard, which is what makes the host-side inverse
        # reorder below exact.
        perm_l = np.argsort(shard_l, kind="stable")
        perm_r = np.argsort(shard_r, kind="stable")
        rows_l = np.bincount(shard_l, minlength=H).astype(np.int64)
        rows_r = np.bincount(shard_r, minlength=H).astype(np.int64)
        # Exact per-shard output counts -> uniform static caps (the
        # max over shards, so one compiled program serves every shard).
        m_s = np.zeros(H, np.int64)
        np.add.at(m_s, key_shard, count_l * count_r)
        ur_s = np.zeros(H, np.int64)
        np.add.at(ur_s, key_shard, np.where(count_l == 0, count_r, 0))
        ul_s = np.zeros(H, np.int64)
        np.add.at(ul_s, key_shard, np.where(count_r == 0, count_l, 0))
        cap_m_s = _pow2_at_least(max(int(m_s.max()), 1))
        cap_r_s = (
            _pow2_at_least(max(int(ur_s.max()), 1))
            if how in (JoinType.RIGHT, JoinType.OUTER)
            else 0
        )
        cap_l_s = (
            _pow2_at_least(max(int(ul_s.max()), 1))
            if how in (JoinType.LEFT, JoinType.OUTER)
            else 0
        )
        staged_l, ck_l = self._stage_partitioned_side(
            lt, m.left_source_op, cols_l, kl, perm_l, rows_l, K,
            left_sel, nl, key_space_sig, H, "L",
        )
        if staged_l is None:
            return None
        staged_r, ck_r = self._stage_partitioned_side(
            rt, m.right_source_op, cols_r, kr, perm_r, rows_r, K,
            right_sel, nr, key_space_sig, H, "R",
        )
        if staged_r is None:
            return None
        outs = self._run_partitioned_join(
            m, staged_l, staged_r, ck_l, ck_r, out_plan, K, H,
            cap_m_s, cap_r_s, cap_l_s,
        )
        if outs is None:
            return None
        # Inverse reorder to the engine's emission order. Matched pairs:
        # the device emits probe-row-major per shard; the engine emits
        # probe-row-major over the ORIGINAL probe order with per-probe
        # build matches contiguous — so a stable argsort of the emitted
        # original probe indices (fanout-repeated) is the exact inverse.
        fan_r = count_l[kr[perm_r]]
        order_m = np.argsort(
            np.repeat(perm_r, fan_r), kind="stable"
        )
        emit_r = perm_r[(count_l[kr] == 0)[perm_r]]
        order_r = np.argsort(emit_r, kind="stable")
        emit_l = perm_l[(count_r[kl] == 0)[perm_l]]
        order_l = np.argsort(emit_l, kind="stable")
        sect = cap_m_s + cap_r_s + cap_l_s
        data = {}
        for ci, (side, src, out_name, dt) in enumerate(out_plan):
            arr = np.asarray(outs[ci]).reshape(H, sect)
            segs = [
                np.concatenate(
                    [arr[h, : m_s[h]] for h in range(H)]
                )[order_m]
            ]
            off = cap_m_s
            if cap_r_s:
                segs.append(
                    np.concatenate(
                        [arr[h, off : off + ur_s[h]] for h in range(H)]
                    )[order_r]
                )
                off += cap_r_s
            if cap_l_s:
                segs.append(
                    np.concatenate(
                        [arr[h, off : off + ul_s[h]] for h in range(H)]
                    )[order_l]
                )
            a = np.concatenate(segs) if len(segs) > 1 else segs[0]
            if dt == DataType.STRING:
                codes = a.astype(np.int32)
                d2 = (lt if side == 0 else rt).dictionaries.get(src)
                if d2 is None:
                    return None
                if (codes < 0).any():
                    vocab = np.asarray(list(d2.values()), dtype=object)
                    vals = np.empty(len(codes), dtype=object)
                    neg = codes < 0
                    vals[~neg] = vocab[codes[~neg]]
                    vals[neg] = ""
                    data[out_name] = vals
                else:
                    data[out_name] = DictColumn(codes, d2)
            else:
                data[out_name] = a.astype(host_dtype(dt))
        return RowBatch.from_pydict(
            m.out_relation, data, eow=True, eos=True
        )

    def _stage_partitioned_side(
        self, table, src_op, cols_needed, kk, perm, rows_s, K,
        sel, n_expect, key_space_sig, H, tag,
    ):
        """Read-filter-permute-stage one join side shard-major, with the
        same residency registration and OOM clear-and-retry policy as
        _stage_cached (which cannot express a reorder: its row_sel is
        an order-preserving boolean mask)."""
        from pixie_tpu.parallel import staging as _staging

        ck = (
            src_op.table_name,
            (table.min_row_id(), table.end_row_id()),
            tuple(cols_needed),
            src_op.start_time,
            src_op.stop_time,
            self.block_rows,
            f":meshjoin{tag}:{H}:" + repr(key_space_sig),
            K,
            (),
        )
        staged = self._staged_lookup(ck)
        if staged is not None and staged.num_rows == n_expect:
            return staged, ck
        cols, n = read_columns(
            table,
            sorted(set(cols_needed)),
            src_op.start_time,
            src_op.stop_time,
        )
        if sel is not None:
            if len(sel) != n:
                return None, None  # table moved under us
            cols = {c: np.asarray(a)[sel] for c, a in cols.items()}
            n = int(np.count_nonzero(sel))
        if n != n_expect or len(kk) != n:
            return None, None  # table moved under us
        cols = {c: np.asarray(a)[perm] for c, a in cols.items()}
        gids = kk[perm].astype(np.int32)

        def _do():
            return _staging.stage_partitioned(
                self.mesh, cols, gids, rows_s, K,
                block_rows=self.block_rows,
            )

        try:
            staged = _do()
        except Exception as e:
            if "RESOURCE_EXHAUSTED" not in str(e) and (
                "Out of memory" not in str(e)
            ):
                raise
            self._staged_cache.clear(reason="oom")
            staged = _do()
        self._staged_insert(ck, staged, src_op.table_name, ck[1])
        return staged, ck

    def _run_partitioned_join(
        self, m, staged_l, staged_r, ck_l, ck_r, out_plan, K, H,
        cap_m_s, cap_r_s, cap_l_s,
    ):
        """Compile-or-reuse the partitioned merge program. Identical to
        the v1 program except: flatten gathers over the INNER axes only
        (each host assembles its own shard), caps are per-shard, and
        every output concatenates over the hosts axis — per-host layout
        [matched cap_m_s | probe-unmatched cap_r_s | build-unmatched
        cap_l_s], global shape [H * sect]."""
        from pixie_tpu.ops import segment as _segment

        l_names = sorted(staged_l.blocks)
        r_names = sorted(staged_r.blocks)
        l_narrow = sorted(staged_l.narrow_offsets)
        r_narrow = sorted(staged_r.narrow_offsets)
        axes = self.mesh_axes
        inner = axes[1:]
        sig = "|".join(
            [
                "join",
                "joinlane:partitioned",
                f"how:{m.join_op.how.value}",
                "L:" + ",".join(
                    f"{n2}:{a.shape}:{a.dtype}"
                    for n2, a in sorted(staged_l.blocks.items())
                ),
                f"lnarrow:{l_narrow}",
                "R:" + ",".join(
                    f"{n2}:{a.shape}:{a.dtype}"
                    for n2, a in sorted(staged_r.blocks.items())
                ),
                f"rnarrow:{r_narrow}",
                f"caps:{cap_m_s},{cap_r_s},{cap_l_s}",
                "out:" + ";".join(
                    f"{side}:{src}:{dt.name}"
                    for side, src, _o, dt in out_plan
                ),
                f"mesh:{self._mesh_sig}",
            ]
        )
        if sig not in self._program_cache:
            _segment.lane_count("join_partitioned")

            def shard_fn(*arrs):
                i = len(l_names)
                lcols = dict(zip(l_names, arrs[:i]))
                lmask_b, lgids_b = arrs[i], arrs[i + 1]
                i += 2
                rcols = dict(zip(r_names, arrs[i : i + len(r_names)]))
                i += len(r_names)
                rmask_b, rgids_b = arrs[i], arrs[i + 1]
                k_arr = arrs[i + 2]
                i += 3
                lnarrow_vec = rnarrow_vec = None
                if l_narrow:
                    lnarrow_vec = arrs[i]
                    i += 1
                if r_narrow:
                    rnarrow_vec = arrs[i]

                def flatten(a):
                    # Per-device [1, nblk, B] -> THIS HOST's shard only:
                    # gather over the inner axes; the hosts axis stays
                    # partitioned (that is the whole point).
                    x = a[0].reshape(-1)
                    if inner:
                        x = jax.lax.all_gather(x, inner).reshape(-1)
                    return x

                lmask = flatten(lmask_b)
                lgid = flatten(lgids_b).astype(jnp.int32)
                rmask = flatten(rmask_b)
                rgid = flatten(rgids_b).astype(jnp.int32)
                kq = k_arr.astype(jnp.int32)
                # Same sentinels as v1: other shards' keys never appear
                # locally, so K / K+1 still top every local real id.
                lkey = jnp.where(lmask, lgid, kq)
                rkey = jnp.where(rmask, rgid, kq + 1)
                build_rows, probe_rows, _fan, ur, ul = (
                    _segment.local_sort_merge(
                        lkey, rkey, lmask, rmask,
                        cap_m_s, cap_r_s, cap_l_s,
                    )
                )
                outs = []
                for side, src, _o, dt in out_plan:
                    if side == 0:
                        col = flatten(lcols[src])
                        narrow_v = (
                            lnarrow_vec[l_narrow.index(src)]
                            if src in l_narrow
                            else None
                        )
                        midx, uidx_r, uidx_l = build_rows, None, ul
                    else:
                        col = flatten(rcols[src])
                        narrow_v = (
                            rnarrow_vec[r_narrow.index(src)]
                            if src in r_narrow
                            else None
                        )
                        midx, uidx_r, uidx_l = probe_rows, ur, None
                    nside = col.shape[0]
                    odt = jnp.int64 if narrow_v is not None else col.dtype
                    nullv = -1 if dt == DataType.STRING else 0

                    def gath(idx, col=col, narrow_v=narrow_v, nside=nside):
                        g = col[jnp.clip(idx, 0, nside - 1)]
                        if narrow_v is not None:
                            g = g.astype(jnp.int64) + narrow_v
                        return g

                    secs = [gath(midx)]
                    if cap_r_s:
                        secs.append(
                            gath(uidx_r)
                            if uidx_r is not None
                            else jnp.full(cap_r_s, nullv, odt)
                        )
                    if cap_l_s:
                        secs.append(
                            gath(uidx_l)
                            if uidx_l is not None
                            else jnp.full(cap_l_s, nullv, odt)
                        )
                    outs.append(
                        jnp.concatenate(secs) if len(secs) > 1 else secs[0]
                    )
                return tuple(outs)

            n_sharded = len(l_names) + 2 + len(r_names) + 2
            n_repl = 1 + (1 if l_narrow else 0) + (1 if r_narrow else 0)
            program = jax.jit(
                shard_map(
                    shard_fn,
                    mesh=self.mesh,
                    in_specs=tuple(
                        [P(axes)] * n_sharded + [P()] * n_repl
                    ),
                    out_specs=tuple([P(axes[0])] * len(out_plan)),
                    **_SM_CHECK_KW,
                )
            )
            self._program_cache[sig] = (program, 0, None)
            _PROGRAMS.set(len(self._program_cache))
        program = self._program_cache[sig][0]
        args = [staged_l.blocks[n2] for n2 in l_names]
        args.append(staged_l.mask)
        args.append(staged_l.gids)
        args += [staged_r.blocks[n2] for n2 in r_names]
        args.append(staged_r.mask)
        args.append(staged_r.gids)
        args.append(jnp.asarray(K, jnp.int32))
        if l_narrow:
            args.append(
                jnp.asarray(
                    [staged_l.narrow_offsets[n2] for n2 in l_narrow],
                    jnp.int64,
                )
            )
        if r_narrow:
            args.append(
                jnp.asarray(
                    [staged_r.narrow_offsets[n2] for n2 in r_narrow],
                    jnp.int64,
                )
            )
        if faults.ACTIVE:
            faults.check("device.join_dispatch")
        with self._staged_cache.pin(ck_l):
            with self._staged_cache.pin(ck_r):
                with _segment.platform_hint(
                    self.mesh.devices.flat[0].platform
                ):
                    return program(*args)

    # -- device scan (filter/project/limit, no aggregate) --------------------
    def _try_execute_scan(
        self, fragment, relations, table_store, registry, func_ctx
    ) -> Optional[tuple[int, RowBatch]]:
        from pixie_tpu.types.dtypes import host_dtype

        m = match_scan_fragment(fragment, relations)
        if m is None:
            return None
        if m.limit > flags.device_scan_limit_cap:
            return None  # unbounded-ish output: host path wins the fetch
        table = table_store.get_table(m.source_op.table_name)
        if table is None:
            return None
        # String outputs must be bare source columns so codes decode
        # through the table dictionary host-side.
        for name, e in m.out_exprs:
            if m.out_relation.col(name).data_type == DataType.STRING and (
                not isinstance(e, ColumnRef)
            ):
                return None
        named = [(f"pred{i}", p) for i, p in enumerate(m.predicates)]
        named += [(f"out:{name}", e) for name, e in m.out_exprs]
        try:
            evaluator = ExpressionEvaluator(
                named, m.source_relation, registry, func_ctx
            )
        except ValueError:
            return None
        base_cols = set()
        for e in m.predicates:
            base_cols |= referenced_columns(e)
        for _, e in m.out_exprs:
            base_cols |= referenced_columns(e)
        version = (table.min_row_id(), table.end_row_id())
        cache_key = (
            m.source_op.table_name,
            version,
            tuple(sorted(base_cols)),
            m.source_op.start_time,
            m.source_op.stop_time,
            self.block_rows,
            ":scan",
            0,
            (),
        )
        staged = self._stage_cached(
            cache_key, table, m.source_op, base_cols, _KeyPlan(num_groups=0)
        )
        if staged is None:
            return None
        aux = {}
        for name, e in evaluator.named_exprs:
            aux.update(evaluator.build_aux(e, table.dictionaries))
        out_dtypes = []
        for name, e in m.out_exprs:
            schema = m.out_relation.col(name)
            if schema.data_type == DataType.STRING:
                out_dtypes.append(np.dtype(np.int32))  # codes
            else:
                out_dtypes.append(np.dtype(host_dtype(schema.data_type)))
        aux_vals = list(aux.values())
        sig = "|".join(
            [
                "scan",
                ",".join(
                    f"{n2}:{a.shape}:{a.dtype}"
                    for n2, a in sorted(staged.blocks.items())
                ),
                f"narrow:{sorted(staged.narrow_offsets)}",
                f"limit:{m.limit}",
                "preds:" + ";".join(repr(p) for p in m.predicates),
                "outs:" + ";".join(f"{n2}={e!r}" for n2, e in m.out_exprs),
                "aux:" + ",".join(
                    f"{np.shape(v)}:{np.asarray(v).dtype}" for v in aux_vals
                ),
                f"mesh:{self._mesh_sig}",
            ]
        )
        assert f"mesh:{self._mesh_sig}" in sig  # geometry guard (r21)
        entry = self._program_cache.get(sig)
        if entry is None:
            program = self._build_scan_program(
                m, evaluator, staged, list(aux.keys()), out_dtypes
            )
            self._program_cache[sig] = (program, len(aux_vals), None)
            _PROGRAMS.set(len(self._program_cache))
        program = self._program_cache[sig][0]
        args = [staged.blocks[n2] for n2 in sorted(staged.blocks)]
        args.append(staged.mask)
        args.extend(jnp.asarray(v) for v in aux_vals)
        if staged.narrow_offsets:
            args.append(
                jnp.asarray(
                    [
                        staged.narrow_offsets[n2]
                        for n2 in sorted(staged.narrow_offsets)
                    ],
                    jnp.int64,
                )
            )
        from pixie_tpu.ops import segment as _segment

        # Pin the staged entry for the dispatch + prefix fetch (r12): a
        # concurrent query's eviction pass must not drop it mid-scan.
        with self._staged_cache.pin(cache_key):
            with _segment.platform_hint(self.mesh.devices.flat[0].platform):
                outs = program(*args)
        written = np.asarray(outs[0])  # [D]
        cap_out = m.limit + staged.block_rows
        ndev = staged.num_devices
        remaining = m.limit
        col_parts: list[list[np.ndarray]] = [[] for _ in m.out_exprs]
        for d in range(ndev):
            take = min(int(written[d]), remaining)
            if take <= 0:
                continue
            for ci in range(len(m.out_exprs)):
                # Slice on device; fetch only the selected prefix.
                col_parts[ci].append(
                    np.asarray(outs[1 + ci][d * cap_out : d * cap_out + take])
                )
            remaining -= take
        out_cols = []
        for (name, e), dt, parts in zip(m.out_exprs, out_dtypes, col_parts):
            arr = (
                np.concatenate(parts)
                if parts
                else np.empty(0, dt)
            )
            schema = m.out_relation.col(name)
            if schema.data_type == DataType.STRING:
                d2 = table.dictionaries.get(e.name)
                if d2 is None:
                    return None
                out_cols.append(DictColumn(arr.astype(np.int32), d2))
            else:
                out_cols.append(arr.astype(dt))
        batch = RowBatch(m.out_relation, out_cols, eow=True, eos=True)
        return m.limit_nid, batch

    def _staged_lookup(self, cache_key):
        # ResidencyPool.get LRU-touches on hit.
        return self._staged_cache.get(cache_key)

    def _stage_cached(
        self,
        cache_key,
        table,
        src_op,
        cols_needed,
        key_plan,
        extra_cols=None,
        f32_cols=None,
        row_sel=None,
    ):
        """Cache-or-stage with the shared OOM clear-and-retry policy.
        Returns the StagedColumns (staged.num_rows tells callers what the
        cursor actually saw). One implementation for the scan and join
        paths — three hand-rolled copies drifted in r4 review.

        ``row_sel`` (r20): a boolean mask over the UNFILTERED read —
        the join pushdown's host-evaluated pre-join predicates. The
        selection applies after the read (boolean-mask indexing keeps
        original row order, matching the host FilterNode), the mask
        length doubling as the table-moved race guard; ``key_plan``
        gids are the caller's FILTERED encoding."""
        staged = self._staged_lookup(cache_key)
        if staged is not None:
            return staged
        base_row = table.min_row_id()
        cols, n = read_columns(
            table,
            sorted(set(cols_needed)),
            src_op.start_time,
            src_op.stop_time,
        )
        if row_sel is not None:
            if len(row_sel) != n:
                return None  # table moved under us
            cols = {c: np.asarray(a)[row_sel] for c, a in cols.items()}
            n = int(np.count_nonzero(row_sel))
        for name, arr in (extra_cols or {}).items():
            if len(arr) != n:
                return None  # table moved under us
            cols[name] = arr
        if key_plan.host_gids is not None and len(key_plan.host_gids) != n:
            return None
        if not extra_cols and row_sel is None:
            # Resident-ingest fast path (r13): assemble the staging from
            # HBM ring windows + a compressed cold tail — the scan/join
            # analogue of the stream loop's per-window substitution.
            staged = self._try_resident_assemble(
                table, src_op, cols, n, key_plan, f32_cols, base_row
            )
            if staged is not None:
                self._staged_insert(
                    cache_key, staged, src_op.table_name, cache_key[1]
                )
                return staged
        try:
            staged = self._stage(cols, n, key_plan, table, f32_cols)
        except Exception as e:
            if "RESOURCE_EXHAUSTED" not in str(e) and (
                "Out of memory" not in str(e)
            ):
                raise
            self._staged_cache.clear(reason="oom")
            staged = None
        if staged is None:
            staged = self._stage(cols, n, key_plan, table, f32_cols)
        self._staged_insert(
            cache_key, staged, src_op.table_name, cache_key[1]
        )
        return staged

    def _try_resident_assemble(
        self, table, src_op, cols, n, key_plan, f32_cols, base_row
    ):
        """Build a StagedColumns from HBM-resident ring windows plus a
        compressed cold tail (r13). Returns None whenever the fast path
        does not apply — no ring, misaligned geometry, zero hits — or on
        any failure (recorded like stream fallbacks; the caller stages
        monolithically, still correct)."""
        ring = self._resident_ring(table, src_op)
        if ring is None or n <= 0 or not cols:
            return None
        try:
            from pixie_tpu.parallel import staging as _staging

            plan = _staging.plan_stream(
                self.mesh,
                cols,
                n,
                ring.window_rows,
                block_rows=self.block_rows,
                f32_cols=f32_cols,
                cell_cols=None,
                num_groups=max(key_plan.num_groups, 1),
                has_gids=key_plan.host_gids is not None,
                gids=key_plan.host_gids,
            )
            if plan.window_rows != ring.window_rows or (
                (plan.d, plan.nblk, plan.b)
                != (ring.d, ring.nblk, ring.b)
            ):
                return None
            col_names = sorted(cols)
            hits = {}
            for w in range(plan.n_windows):
                rows_w = min(
                    plan.window_rows, n - w * plan.window_rows
                )
                rw = ring.lookup(
                    base_row + w * plan.window_rows, rows_w, col_names
                )
                if rw is not None:
                    hits[w] = rw
            if not hits:
                return None  # all-cold: monolithic staging is simpler
            if plan.codecs:
                self._kick_decode_aot(plan)
            dec_cache: dict = {}
            gids = key_plan.host_gids
            win_blocks, win_masks, win_gids = [], [], []
            for w in range(plan.n_windows):
                rows_w = min(
                    plan.window_rows, n - w * plan.window_rows
                )
                rows, packed, pgids, nbytes = _staging.pack_stream_window(
                    plan, cols, gids, w, w in hits
                )
                if w in hits:
                    dev_cols = self._convert_resident_window(
                        plan, hits[w], col_names
                    )
                else:
                    dev_cols = self._put_window_cols(
                        plan, packed, col_names, dec_cache
                    )
                win_blocks.append(dev_cols)
                win_masks.append(
                    _staging._build_mask(
                        self.mesh, plan.d, plan.nblk, plan.b, rows
                    )
                )
                win_gids.append(
                    _staging.put_window_gids(
                        self.mesh, pgids, plan.nblk, plan.b
                    )
                )
                COLD_PROFILE["wire_bytes"] = COLD_PROFILE.get(
                    "wire_bytes", 0.0
                ) + float(nbytes)
                COLD_PROFILE["stage_bytes"] = COLD_PROFILE.get(
                    "stage_bytes", 0.0
                ) + float(
                    plan.window_block_nbytes()
                    + _staging.staged_gid_nbytes(pgids)
                )
            return _staging.concat_stream_windows(
                self.mesh, plan, win_blocks, win_masks, win_gids,
                key_plan.num_groups, key_plan.key_columns,
                table.dictionaries,
            )
        except Exception as e:
            import logging
            import traceback

            key = f"resident-assemble {type(e).__name__}: {e}"
            if key not in self.stream_fallback_errors:
                self.stream_fallback_errors[key] = traceback.format_exc()
                logging.getLogger("pixie_tpu.parallel").warning(
                    "resident assembly failed, staging monolithically: %s",
                    key,
                )
            return None

    def _staged_insert(self, cache_key, staged, table_name, version) -> None:
        """Register a staging with the residency pool: version
        supersession, the byte watermark (hbm_budget_mb), and the LRU
        entry cap all happen inside (serving/residency.py). Also records
        the table's observed staged bytes-per-row, which metadata
        admission control uses to estimate a query's staging cost
        BEFORE the cold stage (serving/admission.py, r13)."""
        self._staged_cache.insert(cache_key, staged, table_name, version)
        from pixie_tpu.serving.residency import staged_nbytes

        from pixie_tpu.parallel.staging import record_observed_bpr

        record_observed_bpr(
            table_name, staged_nbytes(staged), staged.num_rows
        )

    def _build_scan_program(
        self, m: _ScanMatch, evaluator, staged, aux_key_order, out_dtypes
    ):
        axis = self.mesh_axes  # collectives reduce over the FULL mesh
        col_names = sorted(staged.blocks)
        narrow_names = sorted(staged.narrow_offsets)
        limit = m.limit
        cap_out = limit + staged.block_rows
        preds = [
            e for n, e in evaluator.named_exprs if n.startswith("pred")
        ]
        outs = [
            (n[len("out:"):], e)
            for n, e in evaluator.named_exprs
            if n.startswith("out:")
        ]
        jdtypes = [jnp.dtype(dt) for dt in out_dtypes]

        def shard_fn(*arrs):
            i = len(col_names)
            cols = {n: a[0] for n, a in zip(col_names, arrs[:i])}
            mask_all = arrs[i][0]
            i += 1
            end = len(arrs)
            narrow_vec = None
            if narrow_names:
                narrow_vec = arrs[-1]
                end -= 1
            aux = dict(zip(aux_key_order, arrs[i:end]))
            nblk = mask_all.shape[0]
            bufs = tuple(jnp.zeros(cap_out, dt) for dt in jdtypes)

            def cond(carry):
                written, blk, _ = carry
                return (written < limit) & (blk < nblk)

            def body(carry):
                written, blk, bufs = carry
                env = {
                    n: jax.lax.dynamic_index_in_dim(
                        cols[n], blk, 0, keepdims=False
                    )
                    for n in col_names
                }
                for ni, nm in enumerate(narrow_names):
                    env[nm] = env[nm].astype(jnp.int64) + narrow_vec[ni]
                mask = jax.lax.dynamic_index_in_dim(
                    mask_all, blk, 0, keepdims=False
                )
                for p in preds:
                    mask = mask & evaluator.device_eval(p, env, aux)
                vals = [
                    evaluator.device_eval(e, env, aux).astype(dt)
                    for (_, e), dt in zip(outs, jdtypes)
                ]
                # Stable compaction: selected rows first, source order kept.
                key = (~mask).astype(jnp.int32)
                sorted_ops = jax.lax.sort(
                    tuple([key] + vals), num_keys=1, is_stable=True
                )
                cnt = jnp.sum(mask).astype(jnp.int32)
                new_bufs = tuple(
                    jax.lax.dynamic_update_slice(buf, sv, (written,))
                    for buf, sv in zip(bufs, sorted_ops[1:])
                )
                return (
                    jnp.minimum(written + cnt, jnp.int32(limit)),
                    blk + 1,
                    new_bufs,
                )

            written, _, bufs = jax.lax.while_loop(
                cond, body, (jnp.int32(0), jnp.int32(0), bufs)
            )
            return (written.reshape(1),) + bufs

        n_sharded = len(col_names) + 1
        n_repl = len(aux_key_order) + (1 if narrow_names else 0)
        in_specs = tuple([P(axis)] * n_sharded + [P()] * n_repl)
        out_specs = tuple([P(axis)] * (1 + len(jdtypes)))
        return jax.jit(
            shard_map(
                shard_fn,
                mesh=self.mesh,
                in_specs=in_specs,
                out_specs=out_specs,
                **_SM_CHECK_KW,
            )
        )

    def _stage(self, cols, n, key_plan, table, f32_cols=None, int_dicts=None):
        return stage_columns(
            self.mesh,
            cols,
            n,
            gids=key_plan.host_gids,
            num_groups=max(key_plan.num_groups, 1),
            key_columns=key_plan.key_columns,
            dictionaries=table.dictionaries,
            block_rows=self.block_rows,
            f32_cols=f32_cols,
            int_dicts=int_dicts,
        )

    def _cell_cols(self, m: _Match, specs, capacity: int) -> dict:
        """Columns eligible for int-dictionary staging + the cell lane:
        INT64, consumed ONLY as the bare arg of cell-capable UDAs, and
        untouched by predicates/keys. Returns {col: max cardinality} —
        bounded so the per-(group, code) histogram einsum stays on the
        MXU's cheap side (capacity * C <= MATMUL_MAX_SEGMENTS)."""
        from pixie_tpu.ops import segment as _segment

        max_card = min(256, _segment.MATMUL_MAX_SEGMENTS // max(capacity, 1))
        if max_card < 2:
            return {}
        pred_refs = set()
        for p in m.predicates:
            pred_refs |= referenced_columns(p)
        key_refs = set()
        for g in m.agg_op.groups:
            key_refs |= referenced_columns(m.col_exprs[g])
        consumers: dict[str, list] = {}
        for _out, arg_e, uda in specs:
            if not uda.reads_args:
                continue
            for col in referenced_columns(arg_e):
                consumers.setdefault(col, []).append((arg_e, uda))
        out = {}
        for col, cons in consumers.items():
            if col in pred_refs or col in key_refs:
                continue
            try:
                if m.source_relation.col(col).data_type != DataType.INT64:
                    continue
            except KeyError:
                continue
            if all(
                isinstance(ae, ColumnRef) and u.cell_update is not None
                for ae, u in cons
            ):
                out[col] = max_card
        return out

    def _windowize_key_plan(
        self, m: _Match, table, key_plan, base_groups: int
    ):
        """(key_plan with gid' = wid*G + gid, n_windows) or None. Needs
        per-row gids host-side; device key plans are materialized the
        same way the join path does."""
        from pixie_tpu.parallel.staging import read_columns_windowed

        _cols, n, wids, n_windows = read_columns_windowed(
            table,
            [],
            m.source_op.start_time,
            m.source_op.stop_time,
        )
        if n_windows * base_groups > (1 << 22):
            return None  # state tensors would be unreasonable
        gids = key_plan.host_gids
        if gids is None:
            if key_plan.device_expr is None:
                gids = np.zeros(n, np.int32)  # group-by-none
            elif isinstance(key_plan.device_expr, ColumnRef):
                cols2, n2 = read_columns(
                    table,
                    [key_plan.device_expr.name],
                    m.source_op.start_time,
                    m.source_op.stop_time,
                )
                if n2 != n:
                    return None
                gids = np.maximum(cols2[key_plan.device_expr.name], 0)
            elif isinstance(key_plan.device_expr, tuple):
                _, src_col, lut_codes = key_plan.device_expr
                cols2, n2 = read_columns(
                    table,
                    [src_col],
                    m.source_op.start_time,
                    m.source_op.stop_time,
                )
                if n2 != n:
                    return None
                codes = np.maximum(cols2[src_col], 0)
                gids = np.asarray(lut_codes)[codes]
            else:
                return None
        if len(gids) != n or len(wids) != n:
            return None
        combined = (
            wids.astype(np.int64) * base_groups + gids.astype(np.int64)
        )
        return (
            dataclasses.replace(
                key_plan,
                host_gids=combined.astype(np.int32),
                device_expr=None,
                num_groups=n_windows * base_groups,
            ),
            n_windows,
        )

    def _plan_host_any(
        self, m: _Match, specs, key_plan, table
    ) -> dict:
        """any() without predicates needs ONE representative value per
        group — computable host-side from the key plan's gids in a single
        vectorized pass, so the device never pays the ~7ns/row scatter a
        segment-max costs (the only non-sum reduction in the hot configs;
        r5). Returns {out_name: per-group np array (codes for strings)},
        cached per (table version, window, keys, col)."""
        if m.predicates or m.agg_op.stage != AggStage.FULL:
            return {}
        cand = [
            (out, arg_e, uda)
            for out, arg_e, uda in specs
            if uda.name == "any"
            and uda.reads_args
            and isinstance(arg_e, ColumnRef)
        ]
        if not cand:
            return {}
        num_groups = max(key_plan.num_groups, 1)
        # Per-row gids host-side: the generic key plan has them; a
        # dictionary-code key IS the gid column.
        gids = key_plan.host_gids
        gid_col = None
        if gids is None:
            if isinstance(key_plan.device_expr, ColumnRef):
                gid_col = key_plan.device_expr.name
            else:
                return {}
        out = {}
        for out_name, arg_e, uda in cand:
            ck = (
                m.source_op.table_name,
                (table.min_row_id(), table.end_row_id()),
                m.source_op.start_time,
                m.source_op.stop_time,
                repr([m.col_exprs[g] for g in m.agg_op.groups]),
                arg_e.name,
            )
            rep = self._hostany_cache.get(ck)
            if rep is not None:
                # Real LRU: a hit refreshes recency (the r5 version was
                # FIFO despite the comment — the hottest entry could be
                # the first evicted).
                self._hostany_cache.move_to_end(ck)
            else:
                want = [arg_e.name] + ([gid_col] if gid_col else [])
                cols, n = read_columns(
                    table,
                    sorted(set(want)),
                    m.source_op.start_time,
                    m.source_op.stop_time,
                )
                g = gids if gids is not None else np.maximum(cols[gid_col], 0)
                if len(g) != n or n == 0 or int(g.max()) >= num_groups:
                    # Table moved under us (new dictionary codes appended
                    # after planning): fall back to the device path, like
                    # the host_gids length guard.
                    return {}
                vals = cols[arg_e.name]
                rep = np.zeros(num_groups, vals.dtype)
                # Reversed assignment: the LAST write per gid wins, which
                # is the FIRST occurrence in row order — one vectorized
                # pass, no sort.
                rep[g[::-1]] = vals[::-1]
                self._hostany_cache[ck] = rep
                while len(self._hostany_cache) > 32:
                    self._hostany_cache.popitem(last=False)
            out[out_name] = rep
        return out

    def _sketch_f32_cols(self, m: _Match, specs) -> set:
        """FLOAT64 source columns eligible for f32 staging: referenced ONLY
        as bare args of f32-state sketch UDAs (t-digest centroids are f32
        regardless), never by predicates, keys, or computed expressions —
        staging them f32 halves their host→HBM bytes at zero end-to-end
        precision change (cold staging is transfer-bound)."""
        from pixie_tpu.types import DataType as _DT

        f64_cols = {
            c.name
            for c in m.source_relation
            if c.data_type == _DT.FLOAT64
        }
        if not f64_cols:
            return set()
        blocked = set()
        for e in m.predicates:
            blocked |= referenced_columns(e)
        for g in m.agg_op.groups:
            blocked |= referenced_columns(m.col_exprs[g])
        out = set()
        for col in f64_cols - blocked:
            consumers = [
                (arg_e, uda)
                for _, arg_e, uda in specs
                if uda.reads_args and col in referenced_columns(arg_e)
            ]
            if consumers and all(
                isinstance(arg_e, ColumnRef) and uda.stage_f32_ok
                for arg_e, uda in consumers
            ):
                out.add(col)
        return out

    # -- compile helpers ----------------------------------------------------
    def _make_evaluator(self, m: _Match, specs, registry, func_ctx):
        named = [(f"pred{i}", p) for i, p in enumerate(m.predicates)]
        for out_name, arg_e, uda in specs:
            if not uda.reads_args:
                continue  # column never read: don't evaluate it either
            named.append((f"arg:{out_name}:0", arg_e))
        for g in m.agg_op.groups:
            named.append((f"key:{g}", m.col_exprs[g]))
        try:
            return ExpressionEvaluator(
                named, m.source_relation, registry, func_ctx
            )
        except ValueError:
            return None

    def _agg_specs(self, m: _Match, registry):
        """[(out_name, source-term arg exprs, uda)] or None if unresolvable."""
        pre_agg_rel_cols = m.col_exprs
        specs = []
        for out_name, agg in m.agg_op.values:
            arg_exprs = [substitute(a, pre_agg_rel_cols) for a in agg.args]
            try:
                types = [
                    expr_data_type(a, m.source_relation, registry)
                    for a in arg_exprs
                ]
            except (KeyError, ValueError):
                return None
            uda = registry.lookup_uda(agg.name, types)
            if uda is None:
                return None
            if not uda.reads_args:
                # Column never read (count): no arg constraints apply.
                specs.append((out_name, arg_exprs[0], uda))
                continue
            if len(arg_exprs) != 1:
                return None  # single-arg UDAs only on the fast path today
            if any(t == DataType.STRING for t in types) and (
                uda.string_args == "values"
            ):
                return None  # needs decoded strings: host engine only
            if types[0] == DataType.STRING and (
                uda.string_args == "hash" or uda.string_state
            ):
                # String identity/decodability requires the table dictionary:
                # only bare source columns qualify; computed string args fall
                # back to the host engine (which latches dictionaries).
                if not isinstance(arg_exprs[0], ColumnRef):
                    return None
            specs.append((out_name, arg_exprs[0], uda))
        return specs

    def _plan_keys(
        self, m: _Match, table, registry, func_ctx, base_cols: set
    ) -> Optional[_KeyPlan]:
        groups = m.agg_op.groups
        if not groups:
            return _KeyPlan(device_expr=None, num_groups=1, key_columns=[])
        if len(groups) == 1:
            g = groups[0]
            e = m.col_exprs[g]
            try:
                t = expr_data_type(e, m.source_relation, registry)
            except (KeyError, ValueError):
                return None
            if t == DataType.STRING and isinstance(e, ColumnRef):
                d = table.dictionaries.get(e.name)
                if d is not None:
                    base_cols.add(e.name)
                    return _KeyPlan(
                        device_expr=e,
                        num_groups=len(d),
                        key_columns=[DictColumn(np.arange(len(d), dtype=np.int32), d)],
                    )
            if t == DataType.STRING:
                lut = self._dict_lut_key(e, table, registry, func_ctx)
                if lut is not None:
                    lut_codes, out_dict, src_col = lut
                    base_cols.add(src_col)
                    return _KeyPlan(
                        device_expr=("lut", src_col, lut_codes),
                        num_groups=len(out_dict),
                        key_columns=[
                            DictColumn(
                                np.arange(len(out_dict), dtype=np.int32),
                                out_dict,
                            )
                        ],
                    )
        # Generic host path: evaluate key exprs over the full columns once,
        # then densify (ref: the reference hashes RowTuples per batch; we
        # pay one vectorized pass, cached per table version + key exprs —
        # except when keys depend on mutable metadata state).
        kp_cacheable = not any(
            _uses_ctx_func(m.col_exprs[g], m.source_relation, registry)
            for g in groups
        )
        kp_key = (
            m.source_op.table_name,
            (table.min_row_id(), table.end_row_id()),
            repr([m.col_exprs[g] for g in groups]),
            m.source_op.start_time,
            m.source_op.stop_time,
        )
        cached = self._keyplan_cache.get(kp_key) if kp_cacheable else None
        if cached is not None:
            self._keyplan_cache.move_to_end(kp_key)
            return cached
        key_refs = set()
        for g in groups:
            key_refs |= referenced_columns(m.col_exprs[g])
        sub_names = [
            c for c in m.source_relation.col_names() if c in key_refs
        ]
        sub_rel = m.source_relation.select(sub_names)
        ev = ExpressionEvaluator(
            [(g, m.col_exprs[g]) for g in groups], sub_rel,
            registry, func_ctx,
        )
        out_rel = MapOp(
            tuple((g, m.col_exprs[g]) for g in groups)
        ).output_relation([sub_rel], registry)
        # Chunked first-touch pass: evaluate + densify per cursor batch
        # instead of materializing the full key columns — at gigarow scale
        # the monolithic evaluation was the cold-path's host-memory spike,
        # and per-chunk np.unique is cheaper than one giant one
        # (VERDICT r3 weakness 7). GroupEncoder assigns stable gids
        # incrementally across chunks by construction.
        enc = GroupEncoder()
        gid_parts: list[np.ndarray] = []
        # Bare string columns keep the table's write-side dictionary, so
        # their codes are chunk-stable. COMPUTED string keys get a fresh
        # dictionary per evaluated batch — re-encode those through one
        # stable dictionary or chunk codes would be incomparable.
        stable_dicts: dict[str, StringDictionary] = {}
        out_dicts: dict[str, StringDictionary] = {}
        cur = table.cursor(m.source_op.start_time, m.source_op.stop_time)
        while not cur.done():
            b = cur.next_batch()
            if b is None:
                break
            if not b.num_rows:
                continue
            key_batch = ev.evaluate(b.select(sub_names), out_rel)
            key_cols = []
            for g, col in zip(groups, key_batch.columns):
                if isinstance(col, DictColumn):
                    if isinstance(m.col_exprs[g], ColumnRef):
                        out_dicts[g] = col.dictionary
                    else:
                        d = stable_dicts.setdefault(g, StringDictionary())
                        col = DictColumn(d.encode(col.decode()), d)
                        out_dicts[g] = d
                key_cols.append(col)
            gid_parts.append(enc.encode(key_cols))
        gids = (
            np.concatenate(gid_parts) if gid_parts else np.empty(0, np.int32)
        )
        key_arrays = enc.key_arrays()
        key_columns = []
        for g, arr in zip(groups, key_arrays):
            if g in out_dicts:
                key_columns.append(
                    DictColumn(arr.astype(np.int32), out_dicts[g])
                )
            else:
                key_columns.append(arr)
        kp = _KeyPlan(
            host_gids=gids, num_groups=enc.num_groups, key_columns=key_columns
        )
        if kp_cacheable:
            version = (table.min_row_id(), table.end_row_id())
            for k in [
                k for k in self._keyplan_cache
                if k[0] == m.source_op.table_name and k[1] != version
            ]:
                del self._keyplan_cache[k]
            self._keyplan_cache[kp_key] = kp
            while len(self._keyplan_cache) > self._keyplan_cache_cap:
                self._keyplan_cache.popitem(last=False)
        return kp

    def _dict_lut_key(self, e, table, registry, func_ctx=None):
        """String key computed by a dict_compatible host func over one string
        column (the ctx['service'] shape): build per-dictionary-value codes."""
        if not isinstance(e, FuncCall):
            return None
        str_cols = [a for a in e.args if isinstance(a, ColumnRef)]
        if len(str_cols) != 1 or not all(
            isinstance(a, (ColumnRef, Constant)) for a in e.args
        ):
            return None
        src = str_cols[0].name
        d = table.dictionaries.get(src)
        if d is None:
            return None
        arg_types = []
        for a in e.args:
            if isinstance(a, ColumnRef):
                arg_types.append(DataType.STRING)
            else:
                arg_types.append(a.data_type)
        udf = registry.lookup_scalar(e.name, arg_types)
        if udf is None or udf.executor != Executor.HOST or not udf.dict_compatible:
            return None
        values = np.asarray(d.values(), dtype=object)
        fn_args = [
            values if isinstance(a, ColumnRef) else a.value for a in e.args
        ] + list(e.init_args)
        if udf.needs_ctx:
            fn_args = [func_ctx] + fn_args
        per_value = np.asarray(udf.fn(*fn_args), dtype=object)
        out_dict = StringDictionary()
        lut_codes = out_dict.encode(per_value)
        return lut_codes.astype(np.int32), out_dict, src

    def _build_aux(self, evaluator, m, key_plan, table, specs) -> dict:
        # key: exprs are materialized by the key plan (codes / LUT / host
        # gids), never via device_eval aux — only predicates and agg args
        # need LUT/constant-code precomputation.
        aux: dict[str, np.ndarray] = {}
        # Hash-mode string args (sketch UDAs): ship a per-dictionary-value
        # content-hash LUT so the device sees the same dictionary-independent
        # identity the host AggNode does (agg_node._arg_array).
        for out, arg_e, uda in specs:
            if (
                uda.reads_args
                and uda.string_args == "hash"
                and isinstance(arg_e, ColumnRef)
            ):
                d = table.dictionaries.get(arg_e.name)
                if d is not None:
                    aux[f"arghash:{arg_e.name}"] = (
                        d.content_hashes().view(np.int64)
                    )
        for name, e in evaluator.named_exprs:
            if name.startswith("key:"):
                continue
            aux.update(evaluator.build_aux(e, table.dictionaries))
        return aux

    # -- the program --------------------------------------------------------
    def _finalize_modes(self, specs, capacity, force_state: bool = False):
        """Per-spec device-finalization mode + packed-output leaf templates.

        Modes: 'devfin' (UDA supplies a traceable device_finalize — the
        numeric reduction fuses into the program, host only formats),
        'fin' (finalize itself traces — fuse it), 'state' (pack raw state,
        finalize on host). Templates are (treedef, [(shape, dtype)..]) of
        whatever the program will pack for that spec, so the single fetched
        buffer can be split back without guessing."""
        cache_key = (
            tuple((uda.name, uda.arg_types) for _, _, uda in specs),
            capacity,
            force_state,
        )
        cached = self._finmode_cache.get(cache_key)
        if cached is not None:
            return cached
        modes = []
        templates = []
        for _, _, uda in specs:
            state_aval = jax.eval_shape(lambda u=uda: u.init(capacity))
            if force_state:  # PARTIAL stage: raw states cross the bridge
                mode = "state"
                out_aval = state_aval
            elif uda.device_finalize is not None:
                mode = "devfin"
                out_aval = jax.eval_shape(uda.device_finalize, state_aval)
            else:
                try:
                    out_aval = jax.eval_shape(uda.finalize, state_aval)
                    mode = "fin"
                except Exception:
                    mode = "state"
                    out_aval = state_aval
            leaves, treedef = jax.tree.flatten(out_aval)
            modes.append(mode)
            templates.append(
                (treedef, [(tuple(l.shape), l.dtype) for l in leaves])
            )
        self._finmode_cache[cache_key] = (modes, templates)
        return modes, templates

    def _pass_plan(self, specs, num_groups: int) -> tuple[int, int]:
        """(per-pass capacity, n_passes): bound state memory for
        high-cardinality group-bys. Sketch UDAs cost KBs per group slot, so
        1e6 distinct keys would OOM a single-pass program; instead the SAME
        compiled program runs n_passes times over the staged (resident)
        blocks, each pass masking to a contiguous gid range via a gid_base
        argument, and the host concatenates the per-pass outputs (the
        spill/recombine strategy for SURVEY 'Hard parts' #1)."""
        per_group = 8  # presence counter
        for _, _, uda in specs:
            st = jax.eval_shape(lambda u=uda: u.init(1))
            per_group += sum(
                int(np.prod(l.shape)) * l.dtype.itemsize
                for l in jax.tree.leaves(st)
            )
        budget = flags.device_group_state_budget_mb * (1 << 20)
        cap_full = _pow2_at_least(max(num_groups, 1))
        fit = max(budget // per_group, 1)
        max_cap = max(1 << (fit.bit_length() - 1), 8)  # largest pow2 <= fit
        capacity = min(cap_full, max_cap)
        n_passes = (max(num_groups, 1) + capacity - 1) // capacity
        return capacity, n_passes

    def _signature(self, m, specs, key_plan, staged, aux_vals, capacity) -> str:
        """Structural identity of the compiled program: expressions, UDA
        set, key mode, block geometry, capacity, aux shapes."""
        from pixie_tpu.ops import segment as _segment

        modes, _ = self._finalize_modes(
            specs, capacity, m.agg_op.stage == AggStage.PARTIAL
        )
        with _segment.platform_hint(self.mesh.devices.flat[0].platform):
            sortlane = int(_segment.sorted_strategy(staged.mask.shape[-1]))
        parts = [
            f"sortlane:{sortlane}",
            "finmodes:" + ",".join(modes),
            f"stage:{m.agg_op.stage.value}",
            ",".join(f"{n}:{a.shape}:{a.dtype}" for n, a in
                     sorted(staged.blocks.items())),
            f"mask:{staged.mask.shape}",
            f"cap:{capacity}",
            f"narrow:{sorted(staged.narrow_offsets)}",
            f"intdict:{sorted(staged.int_dicts)}",
            f"hostgids:{key_plan.host_gids is not None}",
            "preds:" + ";".join(repr(p) for p in m.predicates),
            "aggs:" + ";".join(
                f"{out}={uda.name}({arg_e!r})" for out, arg_e, uda in specs
            ),
            "key:" + (
                "host" if key_plan.host_gids is not None else (
                    f"lut:{key_plan.device_expr[1]}"
                    if isinstance(key_plan.device_expr, tuple)
                    else repr(key_plan.device_expr)
                )
            ),
            "aux:" + ",".join(
                f"{np.shape(v)}:{np.asarray(v).dtype}" for v in aux_vals
            ),
            f"mesh:{self._mesh_sig}",
        ]
        return "|".join(parts)

    # -- per-lane program decomposition (r7) ---------------------------------
    # The monolithic jit(shard_map(scan+merge+finalize)) recompiled as a
    # whole whenever ANY part of the query changed. Decomposed units are
    # cached under their own signatures: the expensive fold executable is
    # keyed by the scan lane alone (no output names, no finalize modes),
    # so a query that differs only in finalize reuses it and compiles
    # only the small finalize unit; init/merge key on the UDA lane set
    # and are shared across staging geometries entirely.

    def _lane_sig(self, specs) -> str:
        """UDA lane identity WITHOUT output names: two queries whose agg
        lanes differ only in what the outputs are called (or how they
        finalize) share fold/init/merge executables. UDAs that never read
        their column (count) also drop the arg expression and overload
        types — the fold never touches the column, so count('time_') and
        count('latency') are the same lane (this is also what lets
        table-create prewarm guess the count lane without knowing which
        column a future query will point it at)."""
        return ";".join(
            f"{uda.name}{uda.arg_types}({arg_e!r})"
            if uda.reads_args
            else f"{uda.name}()"
            for _out, arg_e, uda in specs
        )

    def _uda_set_sig(self, specs) -> str:
        """Coarser still: the UDA set alone (state shapes + merge kinds
        derive from it) — keys the init and merge units."""
        return ",".join(
            f"{uda.name}{uda.arg_types if uda.reads_args else '()'}"
            for _o, _e, uda in specs
        )

    def _fold_signature(
        self, m, specs, key_plan, staged, aux_vals, capacity,
        preds_repr=None,
    ) -> str:
        """Identity of the FOLD unit alone: scan expressions, UDA update
        lanes, key mode, block geometry, capacity, aux shapes — finalize
        modes, agg stage, and output names are excluded (they key the
        finalize unit). Staging geometry is bucketed (staging
        .block_geometry), so two tables whose padded shapes land in the
        same bucket produce the same string — and share one compiled
        executable in-process plus one .jax_cache entry across runs.

        The sort–compact lane decision (r8) is part of the identity: it
        is made at trace time from the per-block row count, so a flag /
        forced-strategy flip must not reuse a fold traced for the other
        lane.

        ``preds_repr`` (r16) overrides the predicate component: the
        predicate-BATCHED fold erases per-query predicates from its
        identity (they enter as data — per-slot term tables — not as
        traced expressions), so every predicate-compatible query shape
        shares one batched executable per batch-width bucket."""
        from pixie_tpu.ops import segment as _segment

        with _segment.platform_hint(self.mesh.devices.flat[0].platform):
            sortlane = int(_segment.sorted_strategy(staged.mask.shape[-1]))
        parts = [
            f"sortlane:{sortlane}",
            ",".join(f"{n}:{a.shape}:{a.dtype}" for n, a in
                     sorted(staged.blocks.items())),
            f"mask:{staged.mask.shape}",
            f"cap:{capacity}",
            f"narrow:{sorted(staged.narrow_offsets)}",
            f"intdict:{sorted(staged.int_dicts)}",
            f"hostgids:{key_plan.host_gids is not None}",
            "preds:" + (
                preds_repr
                if preds_repr is not None
                else ";".join(repr(p) for p in m.predicates)
            ),
            "lanes:" + self._lane_sig(specs),
            "key:" + (
                "host" if key_plan.host_gids is not None else (
                    f"lut:{key_plan.device_expr[1]}"
                    if isinstance(key_plan.device_expr, tuple)
                    else repr(key_plan.device_expr)
                )
            ),
            "aux:" + ",".join(
                f"{np.shape(v)}:{np.asarray(v).dtype}" for v in aux_vals
            ),
            f"mesh:{self._mesh_sig}",
        ]
        return "|".join(parts)

    def _get_program(self, sig: str, build, n_aux: int = 0):
        """Program-cache lookup-or-build shared by every unit."""
        # Geometry guard: every cached executable was traced against ONE
        # mesh geometry, and every signature must carry that geometry.
        # A lookup whose signature names a different geometry than the
        # executor's mesh means a caller mixed executors/meshes — fail
        # loudly instead of silently reusing a stale compiled program.
        # A mismatch means a caller mixed executors/meshes — a
        # structured MeshGeometryError (r23) that routes through the
        # breaker/fallback ladder to the host engine instead of
        # crashing the query path (it is NOT recoverable by degrading:
        # the geometry itself is fine, the caller's signature is not).
        if f"mesh:{self._mesh_sig}" not in sig:
            raise mesh_lib.MeshGeometryError(
                "signature_mismatch",
                f"program signature {sig!r} does not carry this "
                f"executor's mesh geometry {self._mesh_sig!r}",
            )
        entry = self._program_cache.get(sig)
        if entry is None or entry[1] != n_aux:
            self._program_cache[sig] = (build(), n_aux, None)
            _PROGRAMS.set(len(self._program_cache))
            if resattr.ACTIVE:
                # r15: every distinct program unit enters the
                # device_programs registry at build time; the AOT worker
                # enriches it with XLA cost analysis once a Compiled
                # exists.
                resattr.record_program(sig)
        return self._program_cache[sig][0]

    def _unit_programs(
        self, m, specs, evaluator, key_plan, staged, aux_key_order,
        aux_vals, capacity,
    ):
        """(init_p, fold_p, merge_p, fin_p, fold_sig) for a staging
        geometry — each unit cached under its own signature."""
        treedef, leaves = self._state_template(specs, capacity)
        n_leaves = len(leaves)
        lanes = self._uda_set_sig(specs)
        mesh_s = self._mesh_sig
        col_names = sorted(staged.blocks)
        narrow_names = sorted(staged.narrow_offsets)
        int_dict_names = sorted(staged.int_dicts)
        fold_sig = "fold|" + self._fold_signature(
            m, specs, key_plan, staged, aux_vals, capacity
        )
        init_p = self._get_program(
            f"init|{lanes}|cap:{capacity}|mesh:{mesh_s}",
            lambda: self._build_init(specs, capacity),
        )
        fold_p = self._get_program(
            fold_sig,
            lambda: self._build_fold(
                specs, evaluator, key_plan, col_names, narrow_names,
                int_dict_names, aux_key_order, capacity, n_leaves, treedef,
            ),
            n_aux=len(aux_vals),
        )
        merge_p = self._get_program(
            f"merge|{lanes}|cap:{capacity}|mesh:{mesh_s}",
            lambda: self._build_merge(specs, capacity, n_leaves, treedef),
        )
        force_state = m.agg_op.stage == AggStage.PARTIAL
        fin_p = self._get_program(
            f"fin|{lanes}|cap:{capacity}|state:{force_state}|mesh:{mesh_s}",
            lambda: self._build_fin(specs, capacity, force_state, treedef),
        )
        return init_p, fold_p, merge_p, fin_p, fold_sig

    # -- background AOT compilation (r7) -------------------------------------
    def _aot_lower_compile(self, program, avals):
        """jit -> lowered -> compiled, separated so tests can poison it."""
        return program.lower(*avals).compile()

    def _aot_compile_async(
        self, sig: str, program, avals, profile_key: str = "stage_compile"
    ):
        """Future resolving to the AOT-compiled executable of ``program``
        at ``avals``. The lower+compile runs on a background thread so the
        cold XLA compile overlaps host pack and HBM transfer instead of
        preceding them; results cache in _aot_compiled per signature, and
        in-flight compiles dedup through _aot_futures (a query arriving
        while its prewarmed fold is still compiling attaches to the
        running future instead of compiling twice). COLD_PROFILE gains
        ``profile_key`` seconds (stage_compile for the stream fold,
        warm_compile for the warm/monolithic fold, prewarm_compile at
        table create), compile_cache_hit (persistent .jax_cache
        deserializations observed during the compile), and prewarm_hit
        (query folds served by a table-create prewarm, completed or
        still in flight)."""
        import concurrent.futures

        def record_prewarm_hit():
            if sig in self._prewarmed and profile_key == "stage_compile":
                COLD_PROFILE["prewarm_hit"] = COLD_PROFILE.get(
                    "prewarm_hit", 0.0
                ) + 1.0

        done = self._aot_compiled.get(sig)
        if done is not None:
            record_prewarm_hit()
            fut = concurrent.futures.Future()
            fut.set_result(done)
            return fut
        inflight = self._aot_futures.get(sig)
        if inflight is not None and not (
            inflight.done() and inflight.exception() is not None
        ):
            record_prewarm_hit()
            return inflight
        if self._aot_pool is None:
            self._aot_pool = concurrent.futures.ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="aot-compile"
            )

        def work():
            from pixie_tpu.ops import segment as _segment

            hits0 = _PERSISTENT_CACHE_HITS[0]
            t0 = time.perf_counter()
            # Pin the kernel-strategy hint to the MESH platform: this
            # worker thread has no caller TLS hint, and
            # jax.default_backend() can disagree with the mesh (CPU exec
            # graph on a TPU-attached host) — the trace must pick the
            # same lanes the fold signature assumed.
            with _segment.platform_hint(
                self.mesh.devices.flat[0].platform
            ):
                compiled = self._aot_lower_compile(program, avals)
            compile_s = time.perf_counter() - t0
            COLD_PROFILE[profile_key] = COLD_PROFILE.get(
                profile_key, 0.0
            ) + compile_s
            if _PERSISTENT_CACHE_HITS[0] > hits0:
                COLD_PROFILE["compile_cache_hit"] = COLD_PROFILE.get(
                    "compile_cache_hit", 0.0
                ) + 1.0
            if resattr.ACTIVE:
                # r15: the Compiled carries XLA cost analysis — flops +
                # bytes accessed land in the device_programs registry
                # alongside the measured compile seconds.
                resattr.record_program(
                    sig, compile_s=compile_s, compiled=compiled
                )
            self._aot_compiled[sig] = compiled
            return compiled

        # Workers adopt the submitting query's trace context and
        # resource attribution (r15): compile CPU burned for a query
        # samples under that query's label.
        fut = self._aot_pool.submit(trace.attributed(work, phase="compile"))
        self._aot_futures[sig] = fut
        return fut

    def _aot_warm_fold(
        self, m, specs, evaluator, key_plan, staged, aux, capacity
    ):
        """Background-AOT the WARM/monolithic fold (r8, second ROADMAP
        cold-path lever): the streamed windows concatenate into the
        staged-cache entry at a DIFFERENT geometry than the stream
        window, so the first warm query used to compile its fold inline.
        Called at the end of a cold stream, this lowers+compiles that
        warm-geometry fold on the AOT worker while the cold query
        finishes — breakdown key ``warm_compile``; a compile or dispatch
        failure falls back to the in-line jit like the stream fold does.
        Returns the warm fold signature (None when already compiled or
        in flight)."""
        aux_vals = list(aux.values())
        aux_key_order = list(aux.keys())
        init_p, fold_p, _merge_p, _fin_p, fold_sig = self._unit_programs(
            m, specs, evaluator, key_plan, staged, aux_key_order,
            aux_vals, capacity,
        )
        if fold_sig in self._aot_compiled or fold_sig in self._aot_futures:
            return None  # single-window stream: warm sig == stream sig
        axis_name = self.mesh_axes  # full axis tuple: dim0 over every mesh axis
        sharded = NamedSharding(self.mesh, P(axis_name))
        repl = NamedSharding(self.mesh, P())
        _treedef, leaves = self._state_template(specs, capacity)
        d = staged.num_devices
        avals = [
            jax.ShapeDtypeStruct(
                (d,) + tuple(l.shape), l.dtype, sharding=sharded
            )
            for l in leaves
        ]
        for n2 in sorted(staged.blocks):
            a = staged.blocks[n2]
            avals.append(
                jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=a.sharding)
            )
        avals.append(
            jax.ShapeDtypeStruct(
                staged.mask.shape, staged.mask.dtype,
                sharding=staged.mask.sharding,
            )
        )
        if key_plan.host_gids is not None:
            g = staged.gids
            avals.append(
                jax.ShapeDtypeStruct(g.shape, g.dtype, sharding=g.sharding)
            )
        if isinstance(key_plan.device_expr, tuple):
            lut = np.asarray(key_plan.device_expr[2])
            avals.append(
                jax.ShapeDtypeStruct(lut.shape, lut.dtype, sharding=repl)
            )
        for v in aux_vals:
            v = np.asarray(v)
            avals.append(
                jax.ShapeDtypeStruct(v.shape, v.dtype, sharding=repl)
            )
        if staged.narrow_offsets:
            avals.append(
                jax.ShapeDtypeStruct(
                    (len(staged.narrow_offsets),),
                    np.dtype(np.int64),
                    sharding=repl,
                )
            )
        avals.append(
            jax.ShapeDtypeStruct((), np.dtype(np.int32), sharding=repl)
        )
        self._aot_compile_async(
            fold_sig, fold_p, tuple(avals), profile_key="warm_compile"
        )
        return fold_sig

    # -- table-create compile prewarming (r8) --------------------------------
    def prewarm_table(self, table, registry):
        """Speculatively compile, at table-CREATE time, the fold a
        canonical stats query over this table would need (ROADMAP
        cold-path lever; flag ``prewarm_compile``, default off).

        The canonical shape is groupby(first string column).agg(count,
        sum of every FLOAT64 column) at the standard streamed-window
        bucket geometry — the geometry every cold stream window uses
        once the table exceeds one window, independent of the eventual
        row count. The fold signature is produced by the SAME
        _unit_programs path a real query takes, so a matching first
        query finds its executable in _aot_compiled (or attaches to the
        in-flight compile) and records the ``prewarm_hit`` breakdown
        key; a non-matching query just misses — prewarm is opportunistic
        and never affects correctness. Compile time lands in the
        ``prewarm_compile`` breakdown key at create time, off every
        query's critical path. Returns the prewarmed fold signature, or
        None when gated off / the table has no canonical shape."""
        if not flags.prewarm_compile:
            return None
        try:
            return self._prewarm_table_inner(table, registry)
        except Exception as e:
            import traceback

            key = f"{type(e).__name__}: {e}"
            if key not in self.prewarm_errors:
                self.prewarm_errors[key] = traceback.format_exc()
                import logging

                logging.getLogger("pixie_tpu.parallel").warning(
                    "table-create compile prewarm failed (ignored): %s", key
                )
            return None

    def _prewarm_table_inner(self, table, registry):
        import types as _types

        from pixie_tpu.parallel import staging as _staging

        # r12: when a fold-signature store is wired and holds shapes this
        # table's real queries recorded (serving/signatures.py), replay
        # THEM — bit-identical fold signatures through the same
        # _unit_programs path — instead of guessing the canonical shape.
        # The canonical guess remains the cold-start fallback.
        if self.fold_signature_store is not None:
            sigs = [
                sig
                for sig in (
                    self._prewarm_recorded_shape(table, registry, shape)
                    for shape in self.fold_signature_store.shapes(
                        table.name or ""
                    )
                )
                if sig is not None
            ]
            if sigs:
                return sigs[-1]
        rel = table.relation
        str_cols = [c.name for c in rel if c.data_type == DataType.STRING]
        f64_cols = [c.name for c in rel if c.data_type == DataType.FLOAT64]
        if not str_cols or not f64_cols:
            return None
        key_col = str_cols[0]
        count_uda = registry.lookup_uda("count", [DataType.STRING])
        sum_uda = registry.lookup_uda("sum", [DataType.FLOAT64])
        if count_uda is None or sum_uda is None:
            return None
        # Spec order mirrors the conventional agg listing: count first,
        # then per-column sums. count's arg never enters the fold
        # signature (reads_args=False lanes drop it), so any future
        # count column matches.
        specs = [("pw_n", ColumnRef(key_col), count_uda)]
        for cname in f64_cols:
            specs.append((f"pw_sum_{cname}", ColumnRef(cname), sum_uda))
        named = [
            (f"arg:{out}:0", e) for out, e, uda in specs if uda.reads_args
        ]
        named.append((f"key:{key_col}", ColumnRef(key_col)))
        evaluator = ExpressionEvaluator(named, rel, registry, None)
        # Dictionary-code device key (the string group-by fast path); the
        # capacity floor (8) covers every group-by of <= 8 groups.
        key_plan = _KeyPlan(device_expr=ColumnRef(key_col), num_groups=1)
        capacity, _n_passes = self._pass_plan(specs, 1)
        d = self.mesh.devices.size
        window_rows = max(int(flags.streaming_window_rows), 1)
        b, nblk = _staging.block_geometry(window_rows, d, self.block_rows)
        blocks = {
            # String keys stage as frame-of-reference-narrowed uint8
            # codes while the dictionary stays small (< 256 values).
            key_col: _types.SimpleNamespace(
                shape=(d, nblk, b), dtype=np.dtype(np.uint8)
            )
        }
        for cname in f64_cols:
            blocks[cname] = _types.SimpleNamespace(
                shape=(d, nblk, b), dtype=np.dtype(np.float64)
            )
        shim = _types.SimpleNamespace(
            blocks=blocks,
            mask=_types.SimpleNamespace(shape=(d, nblk, b)),
            narrow_offsets={key_col: 0},
            int_dicts={},
        )
        m_shim = _types.SimpleNamespace(
            predicates=[],
            agg_op=_types.SimpleNamespace(stage=AggStage.FULL),
        )
        _treedef, leaves = self._state_template(specs, capacity)
        _init_p, fold_p, _merge_p, _fin_p, fold_sig = self._unit_programs(
            m_shim, specs, evaluator, key_plan, shim, [], [], capacity
        )
        if fold_sig in self._aot_compiled or fold_sig in self._aot_futures:
            self._prewarmed.add(fold_sig)
            return fold_sig
        axis_name = self.mesh_axes  # full axis tuple: dim0 over every mesh axis
        sharded = NamedSharding(self.mesh, P(axis_name))
        repl = NamedSharding(self.mesh, P())
        avals = [
            jax.ShapeDtypeStruct(
                (d,) + tuple(l.shape), l.dtype, sharding=sharded
            )
            for l in leaves
        ]
        avals += [
            jax.ShapeDtypeStruct(
                (d, nblk, b), blocks[n2].dtype, sharding=sharded
            )
            for n2 in sorted(blocks)
        ]
        avals.append(
            jax.ShapeDtypeStruct(
                (d, nblk, b), np.dtype(np.bool_), sharding=sharded
            )
        )
        # No host gids (device dictionary key), no key LUT, no aux; one
        # narrow offset (the key codes) + the gid_base scalar.
        avals.append(
            jax.ShapeDtypeStruct((1,), np.dtype(np.int64), sharding=repl)
        )
        avals.append(
            jax.ShapeDtypeStruct((), np.dtype(np.int32), sharding=repl)
        )
        self._prewarmed.add(fold_sig)
        self._aot_compile_async(
            fold_sig, fold_p, tuple(avals), profile_key="prewarm_compile"
        )
        return fold_sig

    def _prewarm_recorded_shape(self, table, registry, shape: dict):
        """Replay ONE recorded fold shape (serving/signatures.py) through
        the same _unit_programs path a real query takes: recorded key
        column + agg lanes + capacity + EXACT staged block dtypes and
        geometry reproduce the original fold signature bit-for-bit, so
        the restarted process AOT-compiles (or .jax_cache-deserializes)
        precisely the executables its workload will ask for. Returns the
        fold signature, or None when the shape no longer applies (schema
        drift, mesh resize, missing UDA)."""
        import types as _types

        try:
            d, nblk, b = (int(x) for x in shape["geometry"])
            if d != self.mesh.devices.size:
                return None
            key_col = shape["key_col"]
            rel = table.relation
            specs = []
            for i, (uname, col, argts) in enumerate(shape["lanes"]):
                if col is None:
                    # reads_args=False lane (count): the arg never enters
                    # the fold signature; any resolvable overload works.
                    uda = registry.lookup_uda(uname, [DataType.STRING])
                    if uda is None:
                        return None
                    specs.append((f"pw{i}", ColumnRef(key_col), uda))
                    continue
                uda = registry.lookup_uda(
                    uname, [DataType[t] for t in argts]
                )
                if uda is None:
                    return None
                specs.append((f"pw{i}", ColumnRef(col), uda))
            named = [
                (f"arg:{out}:0", e)
                for out, e, uda in specs
                if uda.reads_args
            ]
            named.append((f"key:{key_col}", ColumnRef(key_col)))
            evaluator = ExpressionEvaluator(named, rel, registry, None)
            key_plan = _KeyPlan(
                device_expr=ColumnRef(key_col), num_groups=1
            )
            capacity = int(shape["capacity"])
            blocks = {
                name: _types.SimpleNamespace(
                    shape=(d, nblk, b), dtype=np.dtype(dt)
                )
                for name, dt in shape["blocks"].items()
            }
            narrow = list(shape.get("narrow") or ())
            shim = _types.SimpleNamespace(
                blocks=blocks,
                mask=_types.SimpleNamespace(shape=(d, nblk, b)),
                narrow_offsets={n2: 0 for n2 in narrow},
                int_dicts={},
            )
            m_shim = _types.SimpleNamespace(
                predicates=[],
                agg_op=_types.SimpleNamespace(stage=AggStage.FULL),
            )
            _treedef, leaves = self._state_template(specs, capacity)
            _i, fold_p, _mg, _f, fold_sig = self._unit_programs(
                m_shim, specs, evaluator, key_plan, shim, [], [], capacity
            )
            self._prewarmed.add(fold_sig)
            if fold_sig in self._aot_compiled or (
                fold_sig in self._aot_futures
            ):
                return fold_sig
            axis_name = self.mesh_axes  # full axis tuple: dim0 over every mesh axis
            sharded = NamedSharding(self.mesh, P(axis_name))
            repl = NamedSharding(self.mesh, P())
            avals = [
                jax.ShapeDtypeStruct(
                    (d,) + tuple(l.shape), l.dtype, sharding=sharded
                )
                for l in leaves
            ]
            avals += [
                jax.ShapeDtypeStruct(
                    (d, nblk, b), blocks[n2].dtype, sharding=sharded
                )
                for n2 in sorted(blocks)
            ]
            avals.append(
                jax.ShapeDtypeStruct(
                    (d, nblk, b), np.dtype(np.bool_), sharding=sharded
                )
            )
            if narrow:
                avals.append(
                    jax.ShapeDtypeStruct(
                        (len(narrow),), np.dtype(np.int64), sharding=repl
                    )
                )
            avals.append(
                jax.ShapeDtypeStruct((), np.dtype(np.int32), sharding=repl)
            )
            self._aot_compile_async(
                fold_sig, fold_p, tuple(avals),
                profile_key="prewarm_compile",
            )
            return fold_sig
        except Exception as e:
            import traceback

            key = f"replay {type(e).__name__}: {e}"
            if key not in self.prewarm_errors:
                self.prewarm_errors[key] = traceback.format_exc()
            return None

    def _make_scan_body(
        self,
        specs,
        evaluator,
        col_names,
        narrow_names,
        int_dict_names,
        preds,
        device_key,
        has_key_lut,
        capacity,
        aux,
        narrow_vec,
        key_lut,
        gid_base,
        use_host_gids,
        pred_batch=None,
    ):
        """The per-block scan body shared by the monolithic program, the
        streaming window-fold program, and (r16, ``pred_batch``) the
        predicate-BATCHED fold. carry = (states tuple, presence);
        xs = (cols tuple, mask, gids).

        With ``pred_batch = (int_cols, flt_cols, term_args)`` the body
        serves B queries at once: carry leaves gain a leading slot axis,
        per-query predicates are evaluated as DATA — a (B, T) table of
        (stack, column index, comparison op, threshold) conjunctive
        terms over two dtype-preserving column stacks (int64 for
        int/bool/code columns, float64 for float columns — both casts
        are exact, so each slot's mask is bit-equal to the serial
        predicate evaluation) — and the per-spec state updates vmap over
        the slot axis with env/gids shared. One scan of the staged
        blocks serves the whole batch."""

        def eval_gids(env, blk_mask):
            if device_key is None:
                # mask always exists; a count-only query may stage NO
                # value columns at all.
                return jnp.zeros_like(blk_mask, dtype=jnp.int32)
            if has_key_lut:
                _, src_col, _ = device_key
                return key_lut[jnp.maximum(env[src_col], 0)]
            return evaluator.device_eval(device_key, env, aux).astype(
                jnp.int32
            )

        def body(carry, xs):
            from pixie_tpu.ops import segment as _segment

            states, presence = carry
            blk_cols, blk_mask, blk_gids = xs
            env = dict(zip(col_names, blk_cols))
            for ni, nm in enumerate(narrow_names):
                # Widen frame-of-reference narrowed columns (VPU cast
                # + add; the transfer savings dwarf this).
                env[nm] = env[nm].astype(jnp.int64) + narrow_vec[ni]
            gids = (
                blk_gids if use_host_gids
                else eval_gids(env, blk_mask)
            )
            # This pass owns groups [gid_base, gid_base + capacity);
            # rows outside it are masked and their updates land on a
            # clipped (masked-out) slot.
            gids = gids.astype(jnp.int32) - gid_base
            gid_ok = (gids >= 0) & (gids < capacity)
            gids = jnp.clip(gids, 0, capacity - 1)

            def eval_col(arg_e, uda):
                col = evaluator.device_eval(arg_e, env, aux)
                hkey = (
                    f"arghash:{arg_e.name}"
                    if uda.string_args == "hash"
                    and isinstance(arg_e, ColumnRef)
                    else None
                )
                if hkey is not None and hkey in aux:
                    lut = aux[hkey]
                    col = lut[jnp.clip(col, 0, lut.shape[0] - 1)]
                return col

            def apply_updates(states, presence, mask):
                # Fused-sum lane: every sum-family UDA contributes f32
                # limb rows to ONE shared one-hot einsum (plus the
                # engine's presence row) — the one-hot generation
                # dominates MXU segment sums, so per-UDA calls pay it
                # k+1 times (r4).
                use_fused = _segment.matmul_strategy(capacity)
                fused_slices: dict[str, tuple[int, int]] = {}
                totals = None
                if use_fused:
                    rows = []
                    for out, arg_e, uda in specs:
                        if uda.fused_rows is None:
                            continue
                        if (
                            uda.cell_update is not None
                            and isinstance(arg_e, ColumnRef)
                            and arg_e.name in int_dict_names
                        ):
                            continue  # cell lane serves it
                        col = (
                            eval_col(arg_e, uda) if uda.reads_args
                            else None
                        )
                        r = uda.fused_rows(col, mask)
                        fused_slices[out] = (len(rows), len(rows) + len(r))
                        rows.extend(r)
                    rows.append(mask.astype(jnp.float32))  # presence
                    totals = _segment.limb_einsum_sums(rows, gids, capacity)
                    presence = presence + totals[-1].astype(presence.dtype)
                else:
                    presence = presence + _segment.seg_count(
                        gids, capacity, mask
                    ).astype(presence.dtype)
                # Cell lane: per-column (group, code) histograms via one
                # MXU einsum each; cell-capable UDAs over int-dictionary
                # columns update per CELL instead of per row (r5).
                hists: dict[str, Any] = {}
                for cname in int_dict_names:
                    lut = aux[f"intdict:{cname}"]
                    C = lut.shape[0]
                    if capacity * C > _segment.MATMUL_MAX_SEGMENTS:
                        # Cache reuse under a bigger pass capacity than
                        # the staging's max_card assumed: histogram would
                        # blow the einsum budget — row path (below) takes
                        # over via a LUT gather instead.
                        continue
                    flat = gids * C + env[cname].astype(jnp.int32)
                    h = _segment.limb_einsum_sums(
                        [mask.astype(jnp.float32)], flat, capacity * C
                    )
                    hists[cname] = h[0].astype(jnp.int64).reshape(
                        capacity, C
                    )
                new_states = []
                for (out, arg_e, uda), st in zip(specs, states):
                    if (
                        uda.cell_update is not None
                        and isinstance(arg_e, ColumnRef)
                        and arg_e.name in int_dict_names
                    ):
                        if arg_e.name in hists:
                            new_states.append(
                                uda.cell_update(
                                    st,
                                    hists[arg_e.name],
                                    aux[f"intdict:{arg_e.name}"],
                                )
                            )
                        else:
                            lut = aux[f"intdict:{arg_e.name}"]
                            vals = lut[env[arg_e.name].astype(jnp.int32)]
                            new_states.append(
                                uda.update(st, gids, vals, mask=mask)
                            )
                        continue
                    if out in fused_slices:
                        a, b = fused_slices[out]
                        new_states.append(uda.fused_apply(st, totals[a:b]))
                        continue
                    if not uda.reads_args:
                        # Column never read; gids is a shape-correct dummy.
                        new_states.append(
                            uda.update(st, gids, gids, mask=mask)
                        )
                        continue
                    new_states.append(
                        uda.update(st, gids, eval_col(arg_e, uda), mask=mask)
                    )
                return tuple(new_states), presence

            if pred_batch is None:
                mask = blk_mask
                for p in preds:
                    mask = mask & evaluator.device_eval(p, env, aux)
                mask = mask & gid_ok
                new_states, presence = apply_updates(
                    states, presence, mask
                )
                return (new_states, presence), None
            # Predicate-batched (r16): per-slot masks from the term
            # table, then the same update logic vmapped over slots.
            int_cols, flt_cols, term_args = pred_batch
            (
                t_stack, t_col_i, t_col_f, t_op,
                t_thr_i, t_thr_f, t_lut_i, t_lut_v,
                t_active, slot_on,
            ) = term_args
            base = blk_mask & gid_ok
            ivals = (
                jnp.stack(
                    [env[c].astype(jnp.int64) for c in int_cols]
                )
                if int_cols
                else jnp.zeros((1,) + blk_mask.shape, jnp.int64)
            )
            fvals = (
                jnp.stack(
                    [env[c].astype(jnp.float64) for c in flt_cols]
                )
                if flt_cols
                else jnp.zeros((1,) + blk_mask.shape, jnp.float64)
            )
            iv = ivals[t_col_i]  # (B, T, rows)
            fv = fvals[t_col_f]

            def cmp_select(op, v, t):
                # op ids: 0 ==, 1 !=, 2 <, 3 <=, 4 >, 5 >=
                return (
                    ((op == 0) & (v == t))
                    | ((op == 1) & (v != t))
                    | ((op == 2) & (v < t))
                    | ((op == 3) & (v <= t))
                    | ((op == 4) & (v > t))
                    | ((op == 5) & (v >= t))
                )

            opb = t_op[:, :, None]
            # r18: op 6 = IN-list membership over the int stack via the
            # per-term LUT lanes — any valid member equal to the row's
            # value. Codes compare in int64 like op 0/1 (an unseen
            # string const rides as -1 and matches no row code), so the
            # batched mask is bit-equal to the serial OR-of-equals.
            in_ok = jnp.any(
                (iv[:, :, None, :] == t_lut_i[:, :, :, None])
                & t_lut_v[:, :, :, None],
                axis=2,
            )
            ci = cmp_select(opb, iv, t_thr_i[:, :, None]) | (
                (opb == 6) & in_ok
            )
            cf = cmp_select(opb, fv, t_thr_f[:, :, None])
            term_ok = jnp.where(t_stack[:, :, None] == 0, ci, cf)
            term_ok = term_ok | ~t_active[:, :, None]
            slot_masks = (
                base[None, :]
                & jnp.all(term_ok, axis=1)
                & slot_on[:, None]
            )
            new_states, presence = jax.vmap(
                apply_updates, in_axes=(0, 0, 0)
            )(states, presence, slot_masks)
            return (new_states, presence), None

        return body

    def _merge_states(self, specs, states, presence, ndev, axis):
        """ICI merge — the collective half of the program tail. One
        collective per UDA (the Kelvin step); on a 1-device mesh every
        collective is the identity — skip them (some PJRT backends only
        lower Sum all-reduces anyway). Returns (merged states, presence),
        replicated across the mesh."""
        if ndev == 1:
            return list(states), presence
        presence = jax.lax.psum(presence, axis)
        merged = []
        for (out, _, uda), st in zip(specs, states):
            if uda.merge_kind == MergeKind.PSUM:
                merged.append(jax.tree.map(
                    lambda x: jax.lax.psum(x, axis), st
                ))
            elif uda.merge_kind == MergeKind.PMAX:
                merged.append(jax.tree.map(
                    lambda x: jax.lax.pmax(x, axis), st
                ))
            elif uda.merge_kind == MergeKind.PMIN:
                merged.append(jax.tree.map(
                    lambda x: jax.lax.pmin(x, axis), st
                ))
            else:  # TREE: all_gather states, fold pairwise
                gathered = jax.tree.map(
                    lambda x: jax.lax.all_gather(x, axis), st
                )
                acc = jax.tree.map(lambda x: x[0], gathered)
                for i2 in range(1, ndev):
                    acc = uda.merge(
                        acc, jax.tree.map(lambda x: x[i2], gathered)
                    )
                merged.append(acc)
        return merged, presence

    def _merge_pack_outputs(self, specs, fin_modes, states, presence, ndev, axis):
        """ICI merge + device finalize + single-buffer pack — the fused
        program tail (_merge_states then _finalize_pack in one trace)."""
        merged, presence = self._merge_states(
            specs, states, presence, ndev, axis
        )
        return self._finalize_pack(specs, fin_modes, merged, presence)

    def _finalize_pack(self, specs, fin_modes, merged, presence):
        # Finalize on device where the UDA allows it, then pack every
        # output/state leaf into ONE f64 buffer (ints ride exactly via
        # bitcast) so the host pays a single device fetch per query —
        # each fetch over a remote link costs ~100ms of round trip, and
        # fusing finalize also kills the state re-upload the host
        # quantile computation used to need.
        outs = []
        for mode, (_, _, uda), st in zip(fin_modes, specs, merged):
            if mode == "devfin":
                outs.append(uda.device_finalize(st))
            elif mode == "fin":
                outs.append(uda.finalize(st))
            else:
                outs.append(st)

        def pack(x):
            # int64 must survive exactly (hash codes use all 64 bits)
            # but TPU bitcast s64<->f64 is broken; split into hi/lo
            # 32-bit halves, each exactly representable in f64.
            if jnp.issubdtype(x.dtype, jnp.integer) or x.dtype == jnp.bool_:
                v = jnp.ravel(x).astype(jnp.int64)
                hi = jnp.floor_divide(v, 1 << 32)
                lo = v - hi * (1 << 32)
                return jnp.concatenate(
                    [hi.astype(jnp.float64), lo.astype(jnp.float64)]
                )
            return jnp.ravel(x).astype(jnp.float64)

        parts = [pack(x) for x in jax.tree.leaves(tuple(outs))]
        parts.append(pack(presence))
        return jnp.concatenate(parts)

    def _build_program(
        self, m, specs, evaluator, key_plan, staged, aux_key_order, capacity
    ):
        axis = self.mesh_axes  # collectives reduce over the FULL mesh
        fin_modes, _ = self._finalize_modes(
            specs, capacity, m.agg_op.stage == AggStage.PARTIAL
        )
        col_names = sorted(staged.blocks)
        narrow_names = sorted(staged.narrow_offsets)
        int_dict_names = sorted(staged.int_dicts)
        has_host_gids = key_plan.host_gids is not None
        has_key_lut = isinstance(key_plan.device_expr, tuple)
        device_key = key_plan.device_expr
        ndev = staged.num_devices
        preds = [
            e for n, e in evaluator.named_exprs if n.startswith("pred")
        ]

        def shard_fn(*arrs):
            # Layout: cols..., mask, [gids], [key_lut], aux...,
            # [narrow_offsets], gid_base. Sharded args arrive as
            # [1, nblk, B]; the rest are replicated; gid_base selects this
            # pass's group window for high-cardinality multi-pass
            # execution; narrow_offsets widen frame-of-reference-encoded
            # int columns back to their logical int64 values per block.
            i = len(col_names)
            cols = {n: a[0] for n, a in zip(col_names, arrs[:i])}
            mask_all = arrs[i][0]
            i += 1
            gids_all = None
            if has_host_gids:
                gids_all = arrs[i][0]
                i += 1
            key_lut = None
            if has_key_lut:
                key_lut = arrs[i]
                i += 1
            gid_base = arrs[-1]
            end = -2 if narrow_names else -1
            narrow_vec = arrs[-2] if narrow_names else None
            aux = dict(zip(aux_key_order, arrs[i:end]))
            body = self._make_scan_body(
                specs, evaluator, col_names, narrow_names, int_dict_names,
                preds, device_key, has_key_lut, capacity, aux, narrow_vec,
                key_lut, gid_base, has_host_gids,
            )
            # Implicit presence counter: the host engine only emits observed
            # groups; without this, dictionary slots whose rows were all
            # filtered out (or expired) would surface as phantom zero rows.
            init_states = (
                tuple(uda.init(capacity) for _, _, uda in specs),
                jnp.zeros(capacity, jnp.int64),
            )
            xs = (
                tuple(cols[n] for n in col_names),
                mask_all,
                gids_all if gids_all is not None else mask_all,
            )
            (states, presence), _ = jax.lax.scan(body, init_states, xs)
            return self._merge_pack_outputs(
                specs, fin_modes, states, presence, ndev, axis
            )

        n_sharded = len(col_names) + 1 + (1 if has_host_gids else 0)
        n_repl = (
            (1 if has_key_lut else 0)
            + len(aux_key_order)
            + (1 if narrow_names else 0)
            + 1  # +gid_base
        )
        in_specs = tuple([P(axis)] * n_sharded + [P()] * n_repl)
        return jax.jit(
            shard_map(
                shard_fn,
                mesh=self.mesh,
                in_specs=in_specs,
                out_specs=P(),
                **_SM_CHECK_KW,
            )
        )

    # -- streamed double-buffered staging (r6) -------------------------------
    # The monolithic path stages the WHOLE table in HBM before the first
    # FLOP; the cold query is therefore pack + transfer + compute in
    # sequence (572s of 613s in stage_transfer for the r5 config-1 shape).
    # The streaming path splits the table into fixed row windows and runs a
    # three-stage software pipeline — window k+2 host-packs on a background
    # thread, window k+1 rides an async device_put, window k folds into the
    # carried UDA states on the mesh — so end-to-end time approaches
    # max(pack, transfer, compute) + one window of fill/drain. The fold
    # reuses the exact per-block scan body of the monolithic program; the
    # finish program applies the same collective-merge/finalize/pack tail.

    def _state_template(self, specs, capacity):
        """(treedef, leaf avals) of the fold carry (states tuple, presence)."""
        avals = jax.eval_shape(
            lambda: (
                tuple(uda.init(capacity) for _, _, uda in specs),
                jnp.zeros(capacity, jnp.int64),
            )
        )
        leaves, treedef = jax.tree.flatten(avals)
        return treedef, leaves

    def _build_init(self, specs, capacity):
        """Identity states created ON the mesh with a leading device axis
        (init == merge identity by UDA contract): each device folds its
        own shard; the merge program combines them over ICI."""
        d = self.mesh.devices.size
        axis_name = self.mesh_axes  # full axis tuple: dim0 over every mesh axis
        sharding = NamedSharding(self.mesh, P(axis_name))

        def init():
            st = (
                tuple(uda.init(capacity) for _, _, uda in specs),
                jnp.zeros(capacity, jnp.int64),
            )
            return [
                jnp.broadcast_to(leaf[None], (d,) + leaf.shape)
                for leaf in jax.tree.leaves(st)
            ]

        return jax.jit(init, out_shardings=sharding)

    def _build_fold(
        self,
        specs,
        evaluator,
        key_plan,
        col_names,
        narrow_names,
        int_dict_names,
        aux_key_order,
        capacity,
        n_state_leaves,
        treedef,
    ):
        """The FOLD unit: scan a set of blocks (one stream window, or the
        whole staged table on the warm path), return the updated
        per-device states. No collectives — those live in the merge unit,
        so every fold dispatch is device-local and async, and the fold
        executable is reused by any query whose scan lane matches
        (_fold_signature), regardless of finalize."""
        axis = self.mesh_axes  # collectives reduce over the FULL mesh
        has_host_gids = key_plan.host_gids is not None
        has_key_lut = isinstance(key_plan.device_expr, tuple)
        device_key = key_plan.device_expr
        preds = [
            e for n, e in evaluator.named_exprs if n.startswith("pred")
        ]

        def shard_fn(*arrs):
            # Layout: state leaves..., cols..., mask, [gids], [key_lut],
            # aux..., [narrow_offsets], gid_base.
            carry = jax.tree.unflatten(
                treedef, [a[0] for a in arrs[:n_state_leaves]]
            )
            i = n_state_leaves
            cols = {
                n: a[0]
                for n, a in zip(col_names, arrs[i : i + len(col_names)])
            }
            i += len(col_names)
            mask_all = arrs[i][0]
            i += 1
            gids_all = None
            if has_host_gids:
                gids_all = arrs[i][0]
                i += 1
            key_lut = None
            if has_key_lut:
                key_lut = arrs[i]
                i += 1
            gid_base = arrs[-1]
            end = -2 if narrow_names else -1
            narrow_vec = arrs[-2] if narrow_names else None
            aux = dict(zip(aux_key_order, arrs[i:end]))
            body = self._make_scan_body(
                specs, evaluator, col_names, narrow_names, int_dict_names,
                preds, device_key, has_key_lut, capacity, aux, narrow_vec,
                key_lut, gid_base, has_host_gids,
            )
            xs = (
                tuple(cols[n] for n in col_names),
                mask_all,
                gids_all if gids_all is not None else mask_all,
            )
            carry, _ = jax.lax.scan(body, carry, xs)
            return tuple(leaf[None] for leaf in jax.tree.leaves(carry))

        n_sharded = (
            n_state_leaves + len(col_names) + 1 + (1 if has_host_gids else 0)
        )
        n_repl = (
            (1 if has_key_lut else 0)
            + len(aux_key_order)
            + (1 if narrow_names else 0)
            + 1  # +gid_base
        )
        in_specs = tuple([P(axis)] * n_sharded + [P()] * n_repl)
        out_specs = tuple([P(axis)] * n_state_leaves)
        return jax.jit(
            shard_map(
                shard_fn,
                mesh=self.mesh,
                in_specs=in_specs,
                out_specs=out_specs,
                **_SM_CHECK_KW,
            )
        )

    def _build_merge(self, specs, capacity, n_state_leaves, treedef):
        """The COLLECTIVE-MERGE unit: per-device states in, replicated
        merged states out — one collective per UDA, nothing else. Keyed
        only by (UDA lane set, capacity, mesh), so every query sharing the
        lane set reuses it across staging geometries."""
        axis = self.mesh_axes  # collectives reduce over the FULL mesh
        ndev = self.mesh.devices.size

        def shard_fn(*arrs):
            states, presence = jax.tree.unflatten(
                treedef, [a[0] for a in arrs]
            )
            merged, presence = self._merge_states(
                specs, list(states), presence, ndev, axis
            )
            return tuple(
                jax.tree.leaves((tuple(merged), presence))
            )

        in_specs = tuple([P(axis)] * n_state_leaves)
        out_specs = tuple([P()] * n_state_leaves)
        return jax.jit(
            shard_map(
                shard_fn,
                mesh=self.mesh,
                in_specs=in_specs,
                out_specs=out_specs,
                **_SM_CHECK_KW,
            )
        )

    def _build_fin(self, specs, capacity, force_state, treedef):
        """The FINALIZE unit: replicated merged states -> the single
        packed f64 fetch buffer (device finalize where the UDA allows,
        else raw state). A plain jit — inputs are replicated, no
        shard_map needed — so a changed-finalize query compiles ONLY this
        small unit while reusing the fold and merge executables."""
        fin_modes, _ = self._finalize_modes(specs, capacity, force_state)

        def fn(*leaves):
            states, presence = jax.tree.unflatten(treedef, leaves)
            return self._finalize_pack(
                specs, fin_modes, list(states), presence
            )

        return jax.jit(fn)

    def _stream_execute(
        self, m, specs, evaluator, key_plan, table, cols, n,
        f32_cols, cell_cols, aux, cacheable, base_row=0,
    ):
        """Streamed staging + window fold. Returns (merged, capacity,
        staged_for_cache|None), or None when gated off or on failure (the
        caller then falls back to monolithic staging, still on-device)."""
        try:
            return self._stream_execute_inner(
                m, specs, evaluator, key_plan, table, cols, n,
                f32_cols, cell_cols, aux, cacheable, base_row,
            )
        except mesh_lib.MeshGeometryError:
            # r23: a geometry failure must reach the degradation ladder
            # (re-plan on the surviving geometry, resume from the last
            # window checkpoint) — monolithic staging on the SAME
            # failed geometry would just hit the fault again.
            raise
        except Exception as e:
            import logging
            import traceback

            key = f"{type(e).__name__}: {e}"
            if key not in self.stream_fallback_errors:
                self.stream_fallback_errors[key] = traceback.format_exc()
                logging.getLogger("pixie_tpu.parallel").warning(
                    "streaming stage failed, falling back to monolithic "
                    "staging: %s",
                    key,
                )
            return None

    def _stream_execute_inner(
        self, m, specs, evaluator, key_plan, table, cols, n,
        f32_cols, cell_cols, aux, cacheable, base_row=0,
    ):
        import concurrent.futures
        import types as _types

        from pixie_tpu.ops import segment as _segment
        from pixie_tpu.parallel import staging as _staging

        capacity, n_passes = self._pass_plan(specs, key_plan.num_groups)
        if n_passes != 1:
            # Multi-pass gid windows re-scan the staged blocks once per
            # pass: they need HBM-resident blocks, not a stream.
            return None
        # Resident ingest (r13): when the table has an HBM ring, stream
        # at the RING's window size so plan window w covers exactly ring
        # window (base_row + w·W)/W — a hit substitutes device-resident
        # blocks for the whole pack+transfer of that window.
        ring = self._resident_ring(table, m.source_op)
        window_rows = flags.streaming_window_rows
        if ring is not None:
            window_rows = ring.window_rows
        plan = _staging.plan_stream(
            self.mesh,
            cols,
            n,
            window_rows,
            block_rows=self.block_rows,
            f32_cols=f32_cols,
            cell_cols=cell_cols,
            num_groups=max(key_plan.num_groups, 1),
            has_gids=key_plan.host_gids is not None,
            gids=key_plan.host_gids,
        )
        if ring is not None and (
            plan.window_rows != ring.window_rows
            or (plan.d, plan.nblk, plan.b) != (ring.d, ring.nblk, ring.b)
        ):
            ring = None  # clamped geometry (small table): no aligned hits
        aux = dict(aux)  # int-dict LUTs are stream-local; keep caller's aux clean
        for n2 in sorted(plan.int_dicts):
            aux[f"intdict:{n2}"] = np.asarray(plan.int_dicts[n2])
        aux_vals = list(aux.values())
        aux_key_order = list(aux.keys())
        col_names = sorted(cols)
        narrow_names = sorted(plan.narrow_offsets)
        # Program identity: the bucketed WINDOW geometry (every window
        # shares it by construction, and so does every table whose padded
        # size lands in the same bucket).
        shim = _types.SimpleNamespace(
            blocks={
                name: _types.SimpleNamespace(
                    shape=(plan.d, plan.nblk, plan.b),
                    dtype=plan.block_dtypes[name],
                )
                for name in col_names
            },
            mask=_types.SimpleNamespace(shape=(plan.d, plan.nblk, plan.b)),
            narrow_offsets=plan.narrow_offsets,
            int_dicts=plan.int_dicts,
        )
        treedef, leaves = self._state_template(specs, capacity)
        init_p, fold_p, merge_p, fin_p, fold_sig = self._unit_programs(
            m, specs, evaluator, key_plan, shim, aux_key_order,
            aux_vals, capacity,
        )
        _, templates = self._finalize_modes(
            specs, capacity, m.agg_op.stage == AggStage.PARTIAL
        )

        # Window-level fold checkpointing (r23, flag mesh_fold_checkpoint,
        # multi-axis-CONFIGURED executors only — gated on the FULL
        # geometry, not the current rung, because a resume lands on a
        # DIFFERENT (often flat) degradation rung by construction): the
        # fold's identity is keyed geometry-FREE, and every rung keeps
        # the total device count, so the padded window geometry (and
        # with it the carried state's shape) is invariant across rungs.
        ckpt_key = None
        start_w = 0
        if flags.mesh_fold_checkpoint and len(self._full_mesh_config.axes) > 1:
            ckpt_key = "|".join(
                (
                    re.sub(r"mesh:[^|]*", "mesh:*", fold_sig),
                    f"rows:{n}",
                    f"win:{plan.window_rows}",
                    f"base:{base_row}",
                    m.source_op.table_name,
                )
            )

        axis_name = self.mesh_axes  # full axis tuple: dim0 over every mesh axis
        sharding = NamedSharding(self.mesh, P(axis_name))
        repl = NamedSharding(self.mesh, P())
        has_host_gids = key_plan.host_gids is not None
        # Constant across windows: key LUT, aux, narrow offsets. Committed
        # replicated so they match the AOT-compiled executable's shardings.
        extra_args = []
        if isinstance(key_plan.device_expr, tuple):
            extra_args.append(
                jax.device_put(np.asarray(key_plan.device_expr[2]), repl)
            )
        extra_args.extend(
            jax.device_put(np.asarray(v), repl) for v in aux_vals
        )
        if plan.narrow_offsets:
            extra_args.append(
                jax.device_put(
                    np.asarray(
                        [plan.narrow_offsets[n2] for n2 in narrow_names],
                        np.int64,
                    ),
                    repl,
                )
            )
        gid_base = jax.device_put(np.int32(0), repl)  # single pass
        gids = key_plan.host_gids

        # Background AOT compile (r7): lower+compile the fold program on
        # a worker thread while pack/transfer stream — the 200s-class XLA
        # compile overlaps the staging instead of preceding it. Fold
        # dispatches are deferred (windows keep transferring) until the
        # compile future resolves; a compile failure falls back to the
        # in-line jit path, recorded in stream_fallback_errors.
        fold_fn = None
        fut_c = None
        if flags.aot_compile:
            avals = [
                jax.ShapeDtypeStruct(
                    (plan.d,) + tuple(l.shape), l.dtype, sharding=sharding
                )
                for l in leaves
            ]
            avals += [
                jax.ShapeDtypeStruct(
                    (plan.d, plan.nblk, plan.b),
                    plan.block_dtypes[n2],
                    sharding=sharding,
                )
                for n2 in col_names
            ]
            avals.append(
                jax.ShapeDtypeStruct(
                    (plan.d, plan.nblk, plan.b), np.bool_, sharding=sharding
                )
            )
            if has_host_gids:
                avals.append(
                    jax.ShapeDtypeStruct(
                        (plan.d, plan.nblk, plan.b),
                        plan.gid_dtype,
                        sharding=sharding,
                    )
                )
            avals += [
                jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=a.sharding)
                for a in extra_args
            ]
            avals.append(
                jax.ShapeDtypeStruct((), gid_base.dtype, sharding=repl)
            )
            fut_c = self._aot_compile_async(fold_sig, fold_p, tuple(avals))
        else:
            fold_fn = fold_p

        def prof(key, dt):
            COLD_PROFILE[key] = COLD_PROFILE.get(key, 0.0) + dt
            # r11: per-stream-window device phases join the query's span
            # tree (pack/transfer/compile/fold per window) instead of
            # living only in the COLD_PROFILE dict. Counter-valued keys
            # (bytes, window counts) are not durations — skipped.
            if trace.ACTIVE and key not in (
                "stage_bytes", "wire_bytes", "stream_windows"
            ):
                trace.phase(f"device.{key}", dt)

        def resolve_fold(block: bool) -> bool:
            """Bind fold_fn once the AOT compile is available (or failed).
            With block=False this is a non-blocking poll; the final call
            blocks — by then every window has transferred, so the wait is
            exactly the non-overlapped compile remainder."""
            nonlocal fold_fn
            if fold_fn is not None:
                return True
            if not block and not fut_c.done():
                return False
            t0 = time.perf_counter()
            try:
                fold_fn = fut_c.result()
            except Exception as e:
                import logging
                import traceback

                key = f"aot-compile {type(e).__name__}: {e}"
                if key not in self.stream_fallback_errors:
                    self.stream_fallback_errors[key] = traceback.format_exc()
                    logging.getLogger("pixie_tpu.parallel").warning(
                        "background AOT compile failed, falling back to "
                        "in-line jit: %s",
                        key,
                    )
                fold_fn = fold_p
            prof("stage_compile_wait", time.perf_counter() - t0)
            return True

        win_blocks: list = []
        win_masks: list = []
        win_gids: list = []
        deferred: list = []  # transferred windows awaiting the compile
        inflight: "collections.deque" = collections.deque()
        flat_state = None

        # Resident-window hits: plan windows whose rows are already in
        # HBM (full ring windows only). Their pack is gids-only and
        # their blocks come from a device-side raw→plan convert.
        hits: dict[int, Any] = {}
        if ring is not None:
            for w0 in range(plan.n_windows):
                rows_w = min(
                    plan.window_rows, plan.num_rows - w0 * plan.window_rows
                )
                rw = ring.lookup(
                    base_row + w0 * plan.window_rows, rows_w, col_names
                )
                if rw is not None:
                    hits[w0] = rw
        # Decode programs compile on the AOT worker while the first
        # windows pack/transfer; in-line jit remains the fallback.
        if plan.codecs:
            self._kick_decode_aot(plan)
        dec_cache: dict = {}

        windows_folded = [0]  # dispatches this attempt (resume-aware)

        def dispatch_fold(dev_cols, mask, dev_g):
            nonlocal flat_state
            args = list(flat_state)
            args.extend(dev_cols[n2] for n2 in col_names)
            args.append(mask)
            if has_host_gids:
                args.append(dev_g)
            args.extend(extra_args)
            args.append(gid_base)
            t0 = time.perf_counter()
            # r23: the sharded dispatch runs under the recovery plane —
            # fault sites + collective watchdog; a geometry failure
            # raises out to the degradation ladder.
            flat_state = list(
                self._mesh_dispatch(
                    lambda: fold_fn(*args),
                    what="stream_fold",
                    fold_sig=fold_sig,
                )
            )
            dt = time.perf_counter() - t0
            prof("stage_stream_dispatch", dt)
            if resattr.ACTIVE:
                resattr.record_dispatch(
                    "stream_fold", dt,
                    program=resattr.program_name(fold_sig),
                )
            cm = _cost_model()
            if cm.ACTIVE:
                # r22: padded window geometry is the shape that prices a
                # stream fold (masked rows still flow through the lanes).
                cm.observe(
                    fold_sig, plan.d * plan.nblk * plan.b, dt
                )
            # Double-buffer backpressure: block on window k-2's fold so
            # at most two windows are in flight (one transferring, one
            # packing) — bounds host-pinned buffers and the device
            # transfer queue.
            inflight.append(flat_state[-1])
            if len(inflight) > 2:
                t0 = time.perf_counter()
                jax.block_until_ready(inflight.popleft())
                prof(
                    "stage_stream_compute_wait",
                    time.perf_counter() - t0,
                )
            windows_folded[0] += 1
            if ckpt_key is not None:
                # Window-boundary checkpoint (r23): pull the carried
                # per-device UDA state host-side, bit-exact (numpy copy
                # of the device buffers — no re-merge, no re-order). The
                # pull synchronizes the window, trading the double-buffer
                # overlap for mid-stream resumability; that is the
                # flag's documented cost, and it only applies on
                # multi-axis meshes.
                t0 = time.perf_counter()
                self._save_fold_checkpoint(
                    ckpt_key,
                    start_w + windows_folded[0],
                    [np.asarray(x) for x in flat_state],
                )
                prof("stage_stream_ckpt", time.perf_counter() - t0)

        t_wall0 = time.perf_counter()
        pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="stream-pack"
        )
        try:
            with _segment.platform_hint(self.mesh.devices.flat[0].platform):
                if ckpt_key is not None:
                    # Resume (r23): a prior attempt on a failed geometry
                    # checkpointed its carry state at window boundaries;
                    # adopt it on THIS mesh and refold only the windows
                    # after the last checkpoint. Merge order is
                    # untouched — the carry state is the same per-device
                    # partial the unfaulted fold would hold here, so
                    # sketches and group order stay bit-identical.
                    flat_state, start_w = self._load_fold_checkpoint(
                        ckpt_key, leaves, plan.d, sharding
                    )
                    if start_w:
                        _MESH_RESUMES.inc()
                        with self._geom_lock:
                            self._geom_events["resumes"] += 1
                if flat_state is None:
                    flat_state = list(init_p())
                # Pack workers adopt the query's trace context and
                # attribution (r15): host CPU burned packing windows
                # samples under this query's label, not as anonymous
                # pool-thread time.
                pack_fn = trace.attributed(
                    _staging.pack_stream_window, phase="pack"
                )
                fut = pool.submit(pack_fn, plan, cols, gids, 0, 0 in hits)
                for w in range(plan.n_windows):
                    t0 = time.perf_counter()
                    rows, packed, pgids, nbytes = fut.result()
                    prof("stage_stream_pack_wait", time.perf_counter() - t0)
                    if w + 1 < plan.n_windows:
                        # Window w+1 packs on the background thread while
                        # window w transfers and folds.
                        fut = pool.submit(
                            pack_fn, plan, cols, gids, w + 1,
                            (w + 1) in hits,
                        )
                    t0 = time.perf_counter()
                    if w in hits:
                        # Resident-ingest hit: the window's columns are
                        # already in HBM — convert raw→plan dtypes on
                        # device; only the (tiny) gids traveled.
                        dev_cols = self._convert_resident_window(
                            plan, hits[w], col_names
                        )
                    else:
                        dev_cols = self._put_window_cols(
                            plan, packed, col_names, dec_cache
                        )
                    mask = _staging._build_mask(
                        self.mesh, plan.d, plan.nblk, plan.b, rows
                    )
                    dev_g = _staging.put_window_gids(
                        self.mesh, pgids, plan.nblk, plan.b
                    )
                    dt_put = time.perf_counter() - t0
                    prof("stage_stream_put", dt_put)
                    wbytes = plan.window_block_nbytes() + (
                        _staging.staged_gid_nbytes(pgids)
                    )
                    prof("stage_bytes", float(wbytes))
                    prof("wire_bytes", float(nbytes))
                    if resattr.ACTIVE:
                        # r15: per-window staging row — staged (decoded
                        # HBM) vs wire (codec-compressed) bytes become
                        # attributable per query/tenant.
                        resattr.record_dispatch(
                            "stream_window", dt_put,
                            program=resattr.program_name(fold_sig),
                            rows=rows, staged_bytes=wbytes,
                            wire_bytes=nbytes,
                        )
                    cm = _cost_model()
                    if cm.ACTIVE and w not in hits and wbytes > 0:
                        # r22: staged-bytes/s per wire lane (codec vs
                        # raw) calibrates the codec_min_ratio decision;
                        # resident-ring hits moved ~nothing over the
                        # wire and would pollute either rate.
                        cm.observe_family(
                            "stage|codec" if nbytes < wbytes
                            else "stage|raw",
                            int(wbytes), dt_put,
                        )
                    if cacheable:
                        win_blocks.append(dev_cols)
                        win_masks.append(mask)
                        win_gids.append(dev_g)
                    if w < start_w:
                        # Resumed fold (r23): windows below the
                        # checkpoint are already in the adopted carry
                        # state — transferred for the warm-cache concat,
                        # never refolded.
                        continue
                    if not resolve_fold(block=False):
                        # Compile still running: keep streaming transfers
                        # (the windows land in HBM, where the cacheable
                        # path keeps them anyway) and fold later. Cap
                        # in-flight transfers at two windows so host
                        # buffers pinned by async device_put stay bounded.
                        deferred.append((dev_cols, mask, dev_g))
                        if len(deferred) >= 2:
                            t0 = time.perf_counter()
                            jax.block_until_ready(
                                list(deferred[-2][0].values())
                            )
                            prof(
                                "stage_stream_transfer_wait",
                                time.perf_counter() - t0,
                            )
                        continue
                    for d_args in deferred:
                        dispatch_fold(*d_args)
                    deferred.clear()
                    dispatch_fold(dev_cols, mask, dev_g)
                # Every window is transferred; if the compile is STILL in
                # flight, this wait is the only non-overlapped compile
                # time (stage_compile_wait in the breakdown).
                resolve_fold(block=True)
                for d_args in deferred:
                    dispatch_fold(*d_args)
                deferred.clear()
                t0 = time.perf_counter()
                # The final cross-host merge is a sharded dispatch too:
                # same recovery plane as the per-window folds (r23).
                merged_flat = self._mesh_dispatch(
                    lambda: merge_p(*flat_state),
                    what="stream_merge",
                    fold_sig=fold_sig,
                )
                buf = fin_p(*merged_flat)
                merged = self._unpack_outputs(templates, capacity, buf)
                prof("stage_stream_drain", time.perf_counter() - t0)
        finally:
            pool.shutdown(wait=True)
            prof("stage_overlap", time.perf_counter() - t_wall0)
            prof("stream_windows", float(plan.n_windows))
        if ckpt_key is not None:
            # Success: the fold's answer is out; the checkpoint must not
            # outlive it (a LATER fold of the same identity starts clean).
            with self._geom_lock:
                self._fold_ckpt.pop(ckpt_key, None)
            if start_w:
                self.last_resume_stats = {
                    "resumed_from_window": int(start_w),
                    "refolded_windows": int(plan.n_windows - start_w),
                    "total_windows": int(plan.n_windows),
                }
        staged_for_cache = None
        if cacheable:
            # Concatenate the windows into one monolithic staging so warm
            # queries hit HBM directly (same contract as stage_columns).
            with _timed("stage_concat"):
                staged_for_cache = _staging.concat_stream_windows(
                    self.mesh, plan, win_blocks, win_masks, win_gids,
                    key_plan.num_groups, key_plan.key_columns,
                    table.dictionaries,
                )
            if flags.aot_compile:
                # r8: AOT-compile the WARM fold (the concat geometry —
                # different from the stream window's) on the background
                # thread NOW, so the first warm query over this staging
                # dispatches a ready executable instead of compiling
                # inline. Best-effort: failures fall back to the in-line
                # jit path, recorded like stream compile failures.
                try:
                    self._aot_warm_fold(
                        m, specs, evaluator, key_plan, staged_for_cache,
                        aux, capacity,
                    )
                except Exception as e:
                    import logging
                    import traceback

                    key = f"warm-aot {type(e).__name__}: {e}"
                    if key not in self.stream_fallback_errors:
                        self.stream_fallback_errors[key] = (
                            traceback.format_exc()
                        )
                        logging.getLogger("pixie_tpu.parallel").warning(
                            "warm-fold AOT compile setup failed, first "
                            "warm query will jit inline: %s",
                            key,
                        )
        return merged, capacity, staged_for_cache

    @staticmethod
    def _unpack_outputs(templates, capacity, buf):
        """Split the single fetched f64 buffer back into per-spec values
        (finalized arrays or raw state pytrees, per the build-time
        templates) + the presence counts. Integer leaves were bitcast, so
        the int64 bit patterns round-trip exactly."""
        buf = np.asarray(buf)
        off = 0

        def unpack_int(size):
            nonlocal off
            hi = buf[off : off + size].astype(np.int64)
            lo = buf[off + size : off + 2 * size].astype(np.int64)
            off += 2 * size
            return (hi << 32) + lo

        values = []
        for treedef, leaves in templates:
            out_leaves = []
            for shape, dtype in leaves:
                size = int(np.prod(shape)) if shape else 1
                if np.issubdtype(dtype, np.integer) or dtype == np.bool_:
                    arr = unpack_int(size).astype(dtype).reshape(shape)
                else:
                    arr = buf[off : off + size].astype(dtype).reshape(shape)
                    off += size
                out_leaves.append(arr)
            values.append(jax.tree.unflatten(treedef, out_leaves))
        presence = unpack_int(capacity)
        return values, presence

    def _shared_scan_run(
        self, m, specs, evaluator, key_plan, staged, aux, cache_key
    ):
        """Run the fold through the shared-scan coordinator (r12, flag
        ``shared_scans``): concurrent queries whose coalescing key
        matches share ONE dispatch and each runs only its own finalize.

        The EXACT key is everything the merged states depend on: the
        staged entry's IDENTITY (same arrays, via the cache key + object
        id), the fold signature (predicates, UDA lanes, key mode,
        geometry, aux shapes — output names and finalize modes excluded,
        so queries differing only there coalesce), the agg stage (a
        PARTIAL query's packed buffer holds raw states, a FULL query's
        holds finalized arrays — they must not share an unpack), and a
        content digest of the replicated aux values + key LUT (equal
        shapes with different values must not share).

        r16 widens the compatibility ladder: when this query's
        predicates normalize to data-driven comparison terms
        (``normalize_predicates``), a second predicate-ERASED key is
        offered to the coordinator — queries matching on everything BUT
        their predicates assemble into one batched dispatch
        (``_run_program_batched``) whose per-slot mask lanes evaluate
        each participant's predicates inside a single scan of the staged
        blocks."""
        from pixie_tpu.serving.shared_scan import aux_digest

        aux2 = dict(aux)
        for n2 in sorted(staged.int_dicts):
            aux2[f"intdict:{n2}"] = np.asarray(staged.int_dicts[n2])
        aux_vals = list(aux2.values())
        capacity, _n_passes = self._pass_plan(specs, key_plan.num_groups)
        fold_sig = self._fold_signature(
            m, specs, key_plan, staged, aux_vals, capacity
        )
        digest_vals = list(aux_vals)
        if isinstance(key_plan.device_expr, tuple):
            digest_vals.append(np.asarray(key_plan.device_expr[2]))
        stage = m.agg_op.stage.value
        key = (
            cache_key, fold_sig, stage, aux_digest(digest_vals),
            id(staged),
        )
        batch_key = terms = compute_batch = None
        if flags.shared_scan_predicate_batching:
            terms = normalize_predicates(
                m.predicates, evaluator, staged, aux2
            )
        if terms is not None:
            # Shared (predicate-independent) aux: the predicate consts/
            # LUTs ride the term table as data, so they leave both the
            # batched program's argument list and the compatibility key.
            pred_keys: set = set()
            for name, e in evaluator.named_exprs:
                if name.startswith("pred"):
                    pred_keys |= set(
                        evaluator.build_aux(e, staged.dictionaries)
                    )
            shared_aux = {
                k: v for k, v in aux.items() if k not in pred_keys
            }
            shared2 = dict(shared_aux)
            for n2 in sorted(staged.int_dicts):
                shared2[f"intdict:{n2}"] = np.asarray(
                    staged.int_dicts[n2]
                )
            shared_vals = list(shared2.values())
            erased = self._fold_signature(
                m, specs, key_plan, staged, shared_vals, capacity,
                preds_repr="<batched>",
            )
            sdigest = list(shared_vals)
            if isinstance(key_plan.device_expr, tuple):
                sdigest.append(np.asarray(key_plan.device_expr[2]))
            batch_key = (
                cache_key, erased, stage, aux_digest(sdigest),
                id(staged),
            )
            compute_batch = (
                lambda slot_terms: self._run_program_batched(
                    m, specs, evaluator, key_plan, staged, shared_aux,
                    slot_terms,
                )
            )
            if flags.aot_compile:
                # r17 satellite: compile the B=2 bucket's batched fold
                # in the background NOW — the first real batched
                # dispatch finds it ready instead of jitting inline.
                self._kick_batched_fold_aot(
                    m, specs, evaluator, key_plan, staged, shared_aux,
                    terms,
                )
        return self._shared_scans.run(
            key,
            lambda: self._run_program(
                m, specs, evaluator, key_plan, staged, aux
            ),
            batch_key=batch_key,
            terms=terms,
            compute_batch=compute_batch,
        )

    # -- predicate-batched shared scans (r16) --------------------------------
    # Crescando/SharedDB posture: concurrent queries whose fold shapes
    # agree on everything except their predicates share ONE scan of the
    # staged blocks. The batched fold stacks per-query partial-agg state
    # lanes on a leading slot axis, evaluates each slot's predicates as
    # DATA (a (B, T) table of comparison terms over dtype-exact column
    # stacks), and fans finalize out per query — so the compiled
    # executable is keyed by a predicate-ERASED signature plus pow2
    # batch-width/term buckets, and batch composition changes never
    # recompile.

    def _pred_stacks(self, staged):
        """The two dtype-preserving predicate column stacks: int64 for
        int/bool/code blocks (incl. narrowed columns, which the scan
        body widens to int64 before stacking), float64 for float
        blocks. Cell-lane code columns are excluded (normalization
        refuses them). Derived from the staged geometry alone, so the
        stack layout is part of the predicate-erased signature."""
        int_cols, flt_cols = [], []
        for c in sorted(staged.blocks):
            if c in staged.int_dicts:
                continue
            k = np.dtype(staged.blocks[c].dtype).kind
            if k in "iub":
                int_cols.append(c)
            elif k == "f":
                flt_cols.append(c)
        return int_cols, flt_cols

    @staticmethod
    def _bucket_pow2(n: int, floor: int = 1) -> int:
        c = max(floor, 1)
        while c < n:
            c <<= 1
        return c

    def _build_batched_init(self, specs, capacity, batch):
        """Batched identity states: one init per (UDA set, capacity,
        batch width) — the r7 init unit with a slot axis between the
        device axis and the state."""
        d = self.mesh.devices.size
        axis_name = self.mesh_axes  # full axis tuple: dim0 over every mesh axis
        sharding = NamedSharding(self.mesh, P(axis_name))

        def init():
            st = (
                tuple(uda.init(capacity) for _, _, uda in specs),
                jnp.zeros(capacity, jnp.int64),
            )
            return [
                jnp.broadcast_to(
                    leaf[None, None], (d, batch) + leaf.shape
                )
                for leaf in jax.tree.leaves(st)
            ]

        return jax.jit(init, out_shardings=sharding)

    # term-table argument count of the batched fold (t_stack, t_col_i,
    # t_col_f, t_op, t_thr_i, t_thr_f, t_lut_i, t_lut_v, t_active,
    # slot_on). t_lut_i/t_lut_v are the r18 per-term IN-list LUT lanes:
    # (B, T, L) member values + validity, consulted when t_op == 6.
    _N_TERM_ARGS = 10

    def _build_batched_fold(
        self,
        specs,
        evaluator,
        key_plan,
        col_names,
        narrow_names,
        int_dict_names,
        aux_key_order,
        capacity,
        n_state_leaves,
        treedef,
        int_cols,
        flt_cols,
    ):
        """The batched FOLD unit (r16): same contract as _build_fold —
        device-local, no collectives, per-device states in and out —
        but carry leaves have a leading slot axis and the per-query
        predicate term tables ride as replicated args after the aux
        lane. One compiled executable serves every predicate-compatible
        batch at this (geometry, lanes, batch, terms) bucket."""
        axis = self.mesh_axes  # collectives reduce over the FULL mesh
        has_host_gids = key_plan.host_gids is not None
        has_key_lut = isinstance(key_plan.device_expr, tuple)
        device_key = key_plan.device_expr
        n_term = self._N_TERM_ARGS

        def shard_fn(*arrs):
            # Layout: state leaves..., cols..., mask, [gids], [key_lut],
            # aux..., [narrow_offsets], term table (8), gid_base.
            carry = jax.tree.unflatten(
                treedef, [a[0] for a in arrs[:n_state_leaves]]
            )
            i = n_state_leaves
            cols = {
                n: a[0]
                for n, a in zip(col_names, arrs[i : i + len(col_names)])
            }
            i += len(col_names)
            mask_all = arrs[i][0]
            i += 1
            gids_all = None
            if has_host_gids:
                gids_all = arrs[i][0]
                i += 1
            key_lut = None
            if has_key_lut:
                key_lut = arrs[i]
                i += 1
            gid_base = arrs[-1]
            term_args = arrs[-(n_term + 1) : -1]
            if narrow_names:
                narrow_vec = arrs[-(n_term + 2)]
                aux_end = -(n_term + 2)
            else:
                narrow_vec = None
                aux_end = -(n_term + 1)
            aux = dict(zip(aux_key_order, arrs[i:aux_end]))
            body = self._make_scan_body(
                specs, evaluator, col_names, narrow_names,
                int_dict_names, [], device_key, has_key_lut, capacity,
                aux, narrow_vec, key_lut, gid_base, has_host_gids,
                pred_batch=(int_cols, flt_cols, term_args),
            )
            xs = (
                tuple(cols[n] for n in col_names),
                mask_all,
                gids_all if gids_all is not None else mask_all,
            )
            carry, _ = jax.lax.scan(body, carry, xs)
            return tuple(leaf[None] for leaf in jax.tree.leaves(carry))

        n_sharded = (
            n_state_leaves + len(col_names) + 1
            + (1 if has_host_gids else 0)
        )
        n_repl = (
            (1 if has_key_lut else 0)
            + len(aux_key_order)
            + (1 if narrow_names else 0)
            + n_term
            + 1  # +gid_base
        )
        in_specs = tuple([P(axis)] * n_sharded + [P()] * n_repl)
        out_specs = tuple([P(axis)] * n_state_leaves)
        return jax.jit(
            shard_map(
                shard_fn,
                mesh=self.mesh,
                in_specs=in_specs,
                out_specs=out_specs,
                **_SM_CHECK_KW,
            )
        )

    def _batched_fold_program(
        self, m, specs, evaluator, key_plan, staged, aux_key_order,
        aux_vals, capacity, B, T, L=1,
    ):
        """The batched FOLD unit for one (erased-sig, B, T, L) bucket
        plus the abstract argument shapes its AOT compile needs (L is
        the r18 IN-list LUT lane width). Shared by the dispatch path
        and the speculative kick so both resolve the SAME signature
        (one compile per bucket, in-flight dedup via _aot_futures)."""
        int_cols, flt_cols = self._pred_stacks(staged)
        erased = self._fold_signature(
            m, specs, key_plan, staged, aux_vals, capacity,
            preds_repr="<batched>",
        )
        bsig = f"bfold|{erased}|batch:{B}|terms:{T}|inlist:{L}"
        treedef, leaves = self._state_template(specs, capacity)
        col_names = sorted(staged.blocks)
        narrow_names = sorted(staged.narrow_offsets)
        int_dict_names = sorted(staged.int_dicts)
        fold_p = self._get_program(
            bsig,
            lambda: self._build_batched_fold(
                specs, evaluator, key_plan, col_names, narrow_names,
                int_dict_names, aux_key_order, capacity, len(leaves),
                treedef, int_cols, flt_cols,
            ),
            n_aux=len(aux_vals),
        )
        axis_name = self.mesh_axes  # full axis tuple: dim0 over every mesh axis
        sharded = NamedSharding(self.mesh, P(axis_name))
        repl = NamedSharding(self.mesh, P())
        d = staged.num_devices
        avals = [
            jax.ShapeDtypeStruct(
                (d, B) + tuple(l.shape), l.dtype, sharding=sharded
            )
            for l in leaves
        ]
        for n2 in col_names:
            a = staged.blocks[n2]
            avals.append(
                jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=a.sharding)
            )
        avals.append(
            jax.ShapeDtypeStruct(
                staged.mask.shape, staged.mask.dtype,
                sharding=staged.mask.sharding,
            )
        )
        if key_plan.host_gids is not None:
            g = staged.gids
            avals.append(
                jax.ShapeDtypeStruct(g.shape, g.dtype, sharding=g.sharding)
            )
        if isinstance(key_plan.device_expr, tuple):
            lut = np.asarray(key_plan.device_expr[2])
            avals.append(
                jax.ShapeDtypeStruct(lut.shape, lut.dtype, sharding=repl)
            )
        for v in aux_vals:
            v = np.asarray(v)
            avals.append(
                jax.ShapeDtypeStruct(v.shape, v.dtype, sharding=repl)
            )
        if staged.narrow_offsets:
            avals.append(
                jax.ShapeDtypeStruct(
                    (len(staged.narrow_offsets),), np.dtype(np.int64),
                    sharding=repl,
                )
            )
        # The 10-term table (t_stack..t_thr_f, the (B, T, L) IN LUT
        # lanes, t_active, slot_on) + gid_base.
        for dt in (
            np.int32, np.int32, np.int32, np.int32, np.int64,
            np.float64,
        ):
            avals.append(
                jax.ShapeDtypeStruct((B, T), np.dtype(dt), sharding=repl)
            )
        avals.append(
            jax.ShapeDtypeStruct((B, T, L), np.dtype(np.int64), sharding=repl)
        )
        avals.append(
            jax.ShapeDtypeStruct((B, T, L), np.dtype(np.bool_), sharding=repl)
        )
        avals.append(
            jax.ShapeDtypeStruct((B, T), np.dtype(np.bool_), sharding=repl)
        )
        avals.append(
            jax.ShapeDtypeStruct((B,), np.dtype(np.bool_), sharding=repl)
        )
        avals.append(
            jax.ShapeDtypeStruct((), np.dtype(np.int32), sharding=repl)
        )
        return bsig, fold_p, tuple(avals)

    def _kick_batched_fold_aot(
        self, m, specs, evaluator, key_plan, staged, shared_aux, terms
    ) -> None:
        """Speculative background compile of the batched fold at the
        B=2 bucket (the soak's p50 batch width) whenever a query's
        predicates normalize: by the time two predicate-compatible
        queries actually coalesce, their bucket's executable is
        compiled (or compiling) on the AOT worker instead of jitting
        inline under the batch's leader. Best-effort and deduped per
        bucket — a kick that never gets used costs one background
        compile, once."""
        try:
            aux = dict(shared_aux)
            for n2 in sorted(staged.int_dicts):
                aux[f"intdict:{n2}"] = np.asarray(staged.int_dicts[n2])
            capacity, _n_passes = self._pass_plan(
                specs, key_plan.num_groups
            )
            bsig, fold_p, avals = self._batched_fold_program(
                m, specs, evaluator, key_plan, staged,
                list(aux.keys()), list(aux.values()), capacity,
                2, self._bucket_pow2(max(len(terms), 1)),
                self._bucket_pow2(
                    max([len(t[5]) for t in terms] + [1])
                ),
            )
            self._aot_compile_async(
                bsig, fold_p, avals, profile_key="batched_compile"
            )
        except Exception:
            import logging

            logging.getLogger("pixie_tpu.parallel").warning(
                "batched-fold AOT kick failed (ignored)", exc_info=True
            )

    def _run_program_batched(
        self, m, specs, evaluator, key_plan, staged, aux, slot_terms
    ):
        """Execute ONE batched fold dispatch serving ``len(slot_terms)``
        predicate-compatible queries, and fan the results out per slot.
        The slot/term axes pad to pow2 buckets so compiled programs are
        reused across batch compositions; the merge and finalize units
        are the EXACT r7 executables the serial path uses, applied to
        each slot's state slice — per-query results are bit-identical
        to serial execution by construction of the mask lanes."""
        aux = dict(aux)
        for n2 in sorted(staged.int_dicts):
            aux[f"intdict:{n2}"] = np.asarray(staged.int_dicts[n2])
        aux_vals = list(aux.values())
        aux_key_order = list(aux.keys())
        capacity, n_passes = self._pass_plan(specs, key_plan.num_groups)
        int_cols, flt_cols = self._pred_stacks(staged)
        i_idx = {c: i for i, c in enumerate(int_cols)}
        f_idx = {c: i for i, c in enumerate(flt_cols)}
        nslots = len(slot_terms)
        B = self._bucket_pow2(nslots)
        T = self._bucket_pow2(max([len(t) for t in slot_terms] + [1]))
        # r18: IN-list LUT lane width — the longest member list across
        # every slot's op-6 terms, pow2-bucketed so the executable is
        # shared across IN-list lengths within a bucket.
        L = self._bucket_pow2(
            max([len(t[5]) for terms in slot_terms for t in terms] + [1])
        )
        t_stack = np.zeros((B, T), np.int32)
        t_col_i = np.zeros((B, T), np.int32)
        t_col_f = np.zeros((B, T), np.int32)
        t_op = np.zeros((B, T), np.int32)
        t_thr_i = np.zeros((B, T), np.int64)
        t_thr_f = np.zeros((B, T), np.float64)
        t_lut_i = np.zeros((B, T, L), np.int64)
        t_lut_v = np.zeros((B, T, L), np.bool_)
        t_active = np.zeros((B, T), np.bool_)
        slot_on = np.zeros((B,), np.bool_)
        for s, terms in enumerate(slot_terms):
            slot_on[s] = True
            for t, (stack, cname, op, thr_i, thr_f, in_vals) in (
                enumerate(terms)
            ):
                t_active[s, t] = True
                t_op[s, t] = op
                if stack == "i":
                    t_col_i[s, t] = i_idx[cname]
                    t_thr_i[s, t] = thr_i
                    if op == 6:
                        t_lut_i[s, t, : len(in_vals)] = in_vals
                        t_lut_v[s, t, : len(in_vals)] = True
                else:
                    t_stack[s, t] = 1
                    t_col_f[s, t] = f_idx[cname]
                    t_thr_f[s, t] = thr_f
        bsig, fold_p, avals = self._batched_fold_program(
            m, specs, evaluator, key_plan, staged, aux_key_order,
            aux_vals, capacity, B, T, L,
        )
        # AOT lane (ROADMAP r16 follow-on): resolve the batched fold
        # through the background compiler like the warm fold — the
        # executable caches per (erased-sig, B, T) bucket (and in the
        # persistent .jax_cache), a speculative kick at predicate-
        # normalization time usually has it compiling already, and a
        # compile failure falls back to the in-line jit recorded in
        # stream_fallback_errors.
        fold_fn = fold_p
        if flags.aot_compile:
            try:
                fold_fn = self._aot_compile_async(
                    bsig, fold_p, avals, profile_key="batched_compile"
                ).result()
            except Exception as e:
                import logging
                import traceback

                key = f"batched-aot {type(e).__name__}: {e}"
                if key not in self.stream_fallback_errors:
                    self.stream_fallback_errors[key] = (
                        traceback.format_exc()
                    )
                    logging.getLogger("pixie_tpu.parallel").warning(
                        "batched-fold AOT compile failed, falling back "
                        "to in-line jit: %s", key,
                    )
                fold_fn = fold_p
        treedef, leaves = self._state_template(specs, capacity)
        lanes = self._uda_set_sig(specs)
        mesh_s = self._mesh_sig
        col_names = sorted(staged.blocks)
        init_p = self._get_program(
            f"binit|{lanes}|cap:{capacity}|batch:{B}|mesh:{mesh_s}",
            lambda: self._build_batched_init(specs, capacity, B),
        )
        # Merge/finalize are the SAME cached units serial queries use.
        merge_p = self._get_program(
            f"merge|{lanes}|cap:{capacity}|mesh:{mesh_s}",
            lambda: self._build_merge(
                specs, capacity, len(leaves), treedef
            ),
        )
        force_state = m.agg_op.stage == AggStage.PARTIAL
        fin_p = self._get_program(
            f"fin|{lanes}|cap:{capacity}|state:{force_state}|mesh:{mesh_s}",
            lambda: self._build_fin(specs, capacity, force_state, treedef),
        )
        _, templates = self._finalize_modes(specs, capacity, force_state)
        # Replicated args are device_put with an explicit sharding so
        # they match the AOT-compiled executable's input shardings (the
        # in-line jit path auto-placed them; a Compiled does not).
        repl = NamedSharding(self.mesh, P())
        args = [staged.blocks[n] for n in col_names] + [staged.mask]
        if key_plan.host_gids is not None:
            args.append(staged.gids)
        if isinstance(key_plan.device_expr, tuple):
            args.append(
                jax.device_put(np.asarray(key_plan.device_expr[2]), repl)
            )
        args.extend(
            jax.device_put(np.asarray(v), repl) for v in aux_vals
        )
        if staged.narrow_offsets:
            args.append(
                jax.device_put(
                    np.asarray(
                        [
                            staged.narrow_offsets[n]
                            for n in sorted(staged.narrow_offsets)
                        ],
                        np.int64,
                    ),
                    repl,
                )
            )
        args.extend(
            jax.device_put(x, repl)
            for x in (
                t_stack, t_col_i, t_col_f, t_op, t_thr_i, t_thr_f,
                t_lut_i, t_lut_v, t_active, slot_on,
            )
        )
        from pixie_tpu.ops import segment as _segment

        per_slot: list[list] = [[] for _ in range(nslots)]
        with _segment.platform_hint(self.mesh.devices.flat[0].platform):
            for p in range(n_passes):
                flat = list(init_p())
                t0 = time.perf_counter()
                gb = jax.device_put(np.int32(p * capacity), repl)
                flat = list(
                    self._mesh_dispatch(
                        lambda: fold_fn(*flat, *args, gb),
                        what="batched_fold",
                        fold_sig=bsig,
                    )
                )
                dt_b = time.perf_counter() - t0
                if resattr.ACTIVE:
                    resattr.record_dispatch(
                        "batched_fold",
                        dt_b,
                        program=resattr.program_name(bsig),
                        rows=staged.num_rows,
                    )
                cm = _cost_model()
                if cm.ACTIVE:
                    cm.observe(bsig, staged.num_rows, dt_b)
                for s in range(nslots):
                    merged_flat = merge_p(*[leaf[:, s] for leaf in flat])
                    buf = fin_p(*merged_flat)
                    per_slot[s].append(
                        self._unpack_outputs(templates, capacity, buf)
                    )
        return [
            self._recombine_passes(per_slot[s], specs, capacity, n_passes)
            for s in range(nslots)
        ]

    def _record_fold_shape(
        self, m, specs, key_plan, staged, capacity, aux
    ) -> None:
        """Persist this query's fold shape for cross-restart prewarm
        replay (r12 satellite) when it is inside the replayable profile:
        device dictionary-code group key, bare-column agg args, no
        predicates/aux/windows. Best-effort — recording failures never
        touch the query."""
        if aux or capacity is None:
            return
        try:
            from pixie_tpu.serving.signatures import shape_from_staged

            shape = shape_from_staged(m, specs, key_plan, staged, capacity)
            if shape is not None:
                self.fold_signature_store.record(
                    m.source_op.table_name, shape
                )
        except Exception:
            import logging

            logging.getLogger("pixie_tpu.parallel").warning(
                "fold-shape record failed (ignored)", exc_info=True
            )

    def _run_program(self, m, specs, evaluator, key_plan, staged, aux):
        """Execute the staged aggregation. Default (program_decompose):
        separately-cached init/fold/merge/finalize units — a query that
        differs only in finalize (output names, FULL vs PARTIAL, a new
        quantile over the same lane) reuses the expensive fold
        executable and compiles only the small finalize unit, and each
        unit compiles faster than the fused whole. The fused
        single-dispatch program remains behind the flag."""
        # Int-dictionary LUTs ride the aux lane (replicated args), so
        # dictionary content can change without recompiling.
        for n2 in sorted(staged.int_dicts):
            aux[f"intdict:{n2}"] = np.asarray(staged.int_dicts[n2])
        aux_vals = list(aux.values())
        aux_key_order = list(aux.keys())
        capacity, n_passes = self._pass_plan(specs, key_plan.num_groups)
        if not flags.program_decompose:
            return self._run_program_fused(
                m, specs, evaluator, key_plan, staged, aux, aux_vals,
                capacity, n_passes,
            )
        col_names = sorted(staged.blocks)
        init_p, fold_p, merge_p, fin_p, fold_sig = self._unit_programs(
            m, specs, evaluator, key_plan, staged, aux_key_order,
            aux_vals, capacity,
        )
        _, templates = self._finalize_modes(
            specs, capacity, m.agg_op.stage == AggStage.PARTIAL
        )
        args = [staged.blocks[n] for n in col_names] + [staged.mask]
        if key_plan.host_gids is not None:
            args.append(staged.gids)
        if isinstance(key_plan.device_expr, tuple):
            args.append(jnp.asarray(key_plan.device_expr[2]))
        args.extend(jnp.asarray(v) for v in aux_vals)
        if staged.narrow_offsets:
            args.append(
                jnp.asarray(
                    [
                        staged.narrow_offsets[n]
                        for n in sorted(staged.narrow_offsets)
                    ],
                    jnp.int64,
                )
            )
        from pixie_tpu.ops import segment as _segment

        # r8: the warm fold may already be AOT-compiled (kicked on the
        # background thread at the end of the cold stream, or by a
        # table-create prewarm). A Compiled requires exactly the avals it
        # was lowered at, so the replicated extras are committed
        # explicitly; any dispatch mismatch falls back to the in-line jit
        # with the error recorded (same contract as the stream fold).
        fold_exec = (
            self._aot_compiled.get(fold_sig) if flags.aot_compile else None
        )
        cargs = None
        if fold_exec is not None:
            repl = NamedSharding(self.mesh, P())
            cargs = [staged.blocks[n] for n in col_names] + [staged.mask]
            if key_plan.host_gids is not None:
                cargs.append(staged.gids)
            if isinstance(key_plan.device_expr, tuple):
                cargs.append(
                    jax.device_put(
                        np.asarray(key_plan.device_expr[2]), repl
                    )
                )
            cargs.extend(
                jax.device_put(np.asarray(v), repl) for v in aux_vals
            )
            if staged.narrow_offsets:
                cargs.append(
                    jax.device_put(
                        np.asarray(
                            [
                                staged.narrow_offsets[n]
                                for n in sorted(staged.narrow_offsets)
                            ],
                            np.int64,
                        ),
                        repl,
                    )
                )
        per_pass = []
        with _segment.platform_hint(self.mesh.devices.flat[0].platform):
            for p in range(n_passes):
                flat = list(init_p())
                folded = False
                if fold_exec is not None:
                    try:
                        gb = jax.device_put(
                            np.int32(p * capacity),
                            NamedSharding(self.mesh, P()),
                        )
                        flat = list(
                            self._mesh_dispatch(
                                lambda: fold_exec(*flat, *cargs, gb),
                                what="warm_fold",
                                fold_sig=fold_sig,
                            )
                        )
                        folded = True
                    except mesh_lib.MeshGeometryError:
                        raise  # r23: recovery ladder, not the jit retry
                    except Exception as e:
                        import logging
                        import traceback

                        fold_exec = None
                        key = f"warm-aot {type(e).__name__}: {e}"
                        if key not in self.stream_fallback_errors:
                            self.stream_fallback_errors[key] = (
                                traceback.format_exc()
                            )
                            logging.getLogger(
                                "pixie_tpu.parallel"
                            ).warning(
                                "AOT warm-fold dispatch failed, falling "
                                "back to in-line jit: %s",
                                key,
                            )
                if not folded:
                    flat = self._mesh_dispatch(
                        lambda: fold_p(*flat, *args, jnp.int32(p * capacity)),
                        what="warm_fold",
                        fold_sig=fold_sig,
                    )
                merged_flat = merge_p(*flat)
                buf = fin_p(*merged_flat)
                # ONE blocking fetch per pass: completion + transfer.
                per_pass.append(
                    self._unpack_outputs(templates, capacity, buf)
                )
        return self._recombine_passes(per_pass, specs, capacity, n_passes)

    def _run_program_fused(
        self, m, specs, evaluator, key_plan, staged, aux, aux_vals,
        capacity, n_passes,
    ):
        col_names = sorted(staged.blocks)
        sig = self._signature(m, specs, key_plan, staged, aux_vals, capacity)
        if f"mesh:{self._mesh_sig}" not in sig:  # geometry guard (r21/r23)
            raise mesh_lib.MeshGeometryError(
                "signature_mismatch",
                f"fused program signature does not carry this "
                f"executor's mesh geometry {self._mesh_sig!r}",
            )
        entry = self._program_cache.get(sig)
        if entry is None or entry[1] != len(aux_vals):
            aux_key_order = list(aux.keys())
            program = self._build_program(
                m, specs, evaluator, key_plan, staged, aux_key_order, capacity
            )
            _, templates = self._finalize_modes(
                specs, capacity, m.agg_op.stage == AggStage.PARTIAL
            )
            self._program_cache[sig] = (program, len(aux_key_order), templates)
            _PROGRAMS.set(len(self._program_cache))
        program, _, templates = self._program_cache[sig]
        args = [staged.blocks[n] for n in col_names] + [staged.mask]
        if key_plan.host_gids is not None:
            args.append(staged.gids)
        if isinstance(key_plan.device_expr, tuple):
            args.append(jnp.asarray(key_plan.device_expr[2]))
        args.extend(jnp.asarray(v) for v in aux_vals)
        if staged.narrow_offsets:
            args.append(
                jnp.asarray(
                    [
                        staged.narrow_offsets[n]
                        for n in sorted(staged.narrow_offsets)
                    ],
                    jnp.int64,
                )
            )
        # First call traces: pin the kernel strategy to the platform the
        # MESH runs on (may differ from jax.default_backend()).
        from pixie_tpu.ops import segment as _segment

        per_pass = []
        with _segment.platform_hint(self.mesh.devices.flat[0].platform):
            for p in range(n_passes):
                buf = self._mesh_dispatch(
                    lambda: program(*args, jnp.int32(p * capacity)),
                    what="fused_fold",
                    fold_sig=sig,
                )
                # ONE blocking fetch per pass: completion + transfer.
                per_pass.append(
                    self._unpack_outputs(templates, capacity, buf)
                )
        return self._recombine_passes(per_pass, specs, capacity, n_passes)

    @staticmethod
    def _recombine_passes(per_pass, specs, capacity, n_passes):
        if n_passes == 1:
            return per_pass[0], capacity
        # Recombine: every leaf (finalized output or state) and the
        # presence counts carry a leading group axis — concatenation
        # reassembles the full gid space across pass windows.
        values = [
            jax.tree.map(
                lambda *leaves: np.concatenate(leaves, axis=0),
                *(vp[0][i] for vp in per_pass),
            )
            for i in range(len(specs))
        ]
        presence = np.concatenate([vp[1] for vp in per_pass])
        return (values, presence), capacity

    # -- finalize -----------------------------------------------------------
    def _partial_state_batch(self, m, specs, key_plan, outputs_and_presence, table):
        """PARTIAL stage: wrap the device-computed states as the StateBatch
        the downstream MERGE agg consumes (ref: the PEM side of
        partial_op_mgr.h:94 serializing partial aggregates). Only observed
        groups ship — a dictionary-keyed plan may carry unobserved slots."""
        from pixie_tpu.exec.agg_node import StateBatch

        values, presence = outputs_and_presence
        n = max(key_plan.num_groups, 1) if m.agg_op.groups else 1
        if m.agg_op.groups:
            keep = np.asarray(presence[:n]) > 0
        else:
            keep = np.ones(1, dtype=bool)
        idx = np.nonzero(keep)[0]
        key_columns = [
            col.take(idx) if isinstance(col, DictColumn)
            else np.asarray(col)[idx]
            for col in key_plan.key_columns
        ]
        states = {}
        arg_dicts = {}
        for (out_name, arg_e, uda), st in zip(specs, values):
            states[out_name] = jax.tree.map(
                lambda a: np.asarray(a)[:n][keep], st
            )
            if uda.string_state and isinstance(arg_e, ColumnRef):
                d = table.dictionaries.get(arg_e.name)
                if d is not None:
                    # Snapshot: device states hold codes into the table's
                    # dictionary; the merge stage translates through this.
                    arg_dicts[out_name] = StringDictionary(list(d.values()))
        return StateBatch(
            key_columns=key_columns,
            states=states,
            num_groups=int(keep.sum()),
            group_names=m.agg_op.groups,
            eow=True,
            eos=True,
            arg_dicts=arg_dicts,
        )

    def _finalize(
        self,
        m,
        specs,
        key_plan,
        capacity,
        outputs_and_presence,
        registry,
        table,
        host_any=None,
        group_range=None,
        eow=True,
        eos=True,
    ):
        host_any = host_any or {}
        device_specs = [s for s in specs if s[0] not in host_any]
        values, presence = outputs_and_presence
        # Use the SAME per-pass capacity the program was compiled with —
        # recomputing modes at staged.capacity could disagree with the
        # packed buffer layout when _pass_plan shrank the window (ADVICE r3).
        modes, _ = self._finalize_modes(device_specs, capacity)
        by_out = {
            s[0]: (s, mode, val)
            for s, mode, val in zip(device_specs, modes, values)
        }
        if group_range is not None:
            # Windowed finalize: this call covers groups
            # [off, off+cnt) — one window's slice of the (window x group)
            # id space.
            off, cnt = group_range
            values = [
                jax.tree.map(lambda a: np.asarray(a)[off : off + cnt], v)
                for v in values
            ]
            by_out = {
                s[0]: (s, mode, val)
                for s, mode, val in zip(device_specs, modes, values)
            }
            presence = np.asarray(presence)[off : off + cnt]
            n = cnt if m.agg_op.groups else 1
        else:
            n = max(key_plan.num_groups, 1) if m.agg_op.groups else 1
        rel = m.agg_op.output_relation([_pre_agg_relation(m, registry)], registry)
        # Only observed groups are emitted (host-engine semantics): drop
        # slots whose rows were all filtered out / expired. Group-by-none
        # keeps its single row (the reference emits one row on empty input).
        if m.agg_op.groups:
            keep = np.asarray(presence[:n]) > 0
        else:
            keep = np.ones(1, dtype=bool)
        out_cols: list = []
        for g, col in zip(m.agg_op.groups, key_plan.key_columns):
            out_cols.append(
                col.take(np.nonzero(keep)[0])
                if isinstance(col, DictColumn)
                else np.asarray(col)[keep]
            )
        from pixie_tpu.types.dtypes import host_dtype

        for out_name, arg_e, uda in specs:
            schema = rel.col(out_name)
            if out_name in host_any:
                rep = np.asarray(host_any[out_name])[:n][keep]
                if schema.data_type == DataType.STRING:
                    src_dict = table.dictionaries.get(arg_e.name)
                    vals2 = (
                        src_dict.decode(rep.astype(np.int32))
                        if src_dict is not None
                        else np.full(len(rep), "", dtype=object)
                    )
                    d = StringDictionary()
                    out_cols.append(DictColumn(d.encode(vals2), d))
                else:
                    out_cols.append(
                        rep.astype(host_dtype(schema.data_type))
                    )
                continue
            _spec, mode, val = by_out[out_name]
            if mode == "state":
                sliced = jax.tree.map(lambda a: np.asarray(a)[:n][keep], val)
                out = uda.finalize(sliced)
            else:
                arr = np.asarray(val)[:n][keep]
                out = (
                    uda.format_output(arr)
                    if mode == "devfin" and uda.format_output is not None
                    else arr
                )
            if schema.data_type == DataType.STRING:
                if uda.string_state:
                    # Code-valued state (any(STRING)): decode through the
                    # table dictionary — matches agg_node._finalized_batch.
                    src_dict = (
                        table.dictionaries.get(arg_e.name)
                        if isinstance(arg_e, ColumnRef)
                        else None
                    )
                    codes = np.asarray(out)
                    vals = (
                        src_dict.decode(codes)
                        if src_dict is not None
                        else np.full(len(codes), "", dtype=object)
                    )
                else:
                    vals = np.asarray(out, dtype=object)
                d = StringDictionary()
                out_cols.append(DictColumn(d.encode(vals), d))
            else:
                out_cols.append(np.asarray(out, dtype=host_dtype(schema.data_type)))
        return RowBatch(rel, out_cols, eow=eow, eos=eos)


def _pre_agg_relation(m: _Match, registry):
    return MapOp(
        tuple((name, e) for name, e in m.col_exprs.items())
    ).output_relation([m.source_relation], registry)


def _uses_ctx_func(expr, relation, registry) -> bool:
    """Does the expression call a needs_ctx (metadata-state) UDF? Such
    results change when k8s metadata churns, with no table write. Resolves
    the actual overload by argument types; only when typing fails does it
    fall back to any-overload (conservative: may disable caching, never
    enables stale results)."""
    if isinstance(expr, FuncCall):
        udf = None
        try:
            types = [expr_data_type(a, relation, registry) for a in expr.args]
            udf = registry.lookup_scalar(expr.name, types)
        except (KeyError, ValueError):
            pass
        if udf is not None:
            if udf.needs_ctx:
                return True
        elif any(
            f.needs_ctx for f in registry.scalar_overloads(expr.name)
        ):
            return True
        return any(_uses_ctx_func(a, relation, registry) for a in expr.args)
    return False
