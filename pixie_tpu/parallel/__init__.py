"""Distributed / device-mesh execution.

Ref: src/carnot/planner/distributed/ (splitter, partial-agg rewrite,
coordinator) and the PEM→Kelvin gRPC data plane it drives. TPU-native
redesign per SURVEY.md §2.6: the data-parallel scatter-gather becomes a
shard_map program over a jax Mesh — each device aggregates its shard of
staged blocks (the PEM role), and the Kelvin merge step becomes XLA
collectives over ICI (psum/pmax/pmin for elementwise UDA states, all_gather
+ tree fold for order-insensitive sketches like t-digest).
"""

from pixie_tpu.parallel.pipeline import MeshExecutor
from pixie_tpu.parallel.staging import StagedColumns, stage_columns

__all__ = ["MeshExecutor", "StagedColumns", "stage_columns"]
