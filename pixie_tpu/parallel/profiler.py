"""Device & HBM resource-attribution recorders (r15).

Ref posture: Google-Wide Profiling (Ren et al., IEEE Micro 2010) —
always-on sampled profiling is affordable when the samples carry
workload attribution — applied to the device side of this engine. Three
ring buffers feed the self-telemetry tables (ingest/self_telemetry.py),
drained the same way finished trace spans are:

  device_programs    one row per compiled device program: the program
                     cache signature (truncated), unit kind (init/fold/
                     merge/fin/decode), XLA cost analysis (flops, bytes
                     accessed) when an AOT compile produced a Compiled,
                     and the measured compile seconds.
  device_dispatches  one row per device dispatch (whole-offload
                     ``fold`` rows from try_execute_fragment, per-window
                     ``stream_fold``/``stream_window`` rows from the
                     streaming stage), stamped with the dispatching
                     thread's ambient (query_id, tenant, phase)
                     attribution (utils/trace.py) — device wall time and
                     staged/decoded bytes become attributable per query.
  hbm_usage          point-in-time residency-pool snapshots (total /
                     pinned / ring bytes, per-table residency), sampled
                     by the pool itself at ``hbm_snapshot_interval_s``
                     cadence plus a forced sample at every telemetry
                     flush.

Design contract (mirrors utils/faults.py and utils/trace.py): call
sites gate on the module-level ``ACTIVE`` bool, synced with the shared
``resource_attribution`` flag — disabled, every hook is one attribute
load + branch, held <1% of the warm fold and transport RTT by
tools/microbench_fault_overhead.py's ``profiler_overhead`` key.
"""

from __future__ import annotations

import collections
import threading
import time
import weakref
from typing import Any, Optional

from pixie_tpu.utils import trace
from pixie_tpu.utils.config import define_flag, flags

define_flag(
    "hbm_snapshot_interval_s",
    1.0,
    help_="Minimum seconds between HBM residency-pool usage snapshots "
    "(hbm_usage self-telemetry rows). Snapshots are taken on pool "
    "mutations at most this often, plus one forced sample at every "
    "self-telemetry flush; 0 samples on every mutation.",
)
define_flag(
    "profiler_buffer_cap",
    8192,
    help_="Ring-buffer capacity per resource-attribution stream "
    "(device_dispatches rows, hbm_usage rows, new device_programs "
    "rows); oldest entries are evicted when telemetry ingestion falls "
    "behind.",
)

# Fast gate, synced with the resource_attribution flag (one attribute
# load + branch per call site when attribution is off).
ACTIVE = False


def refresh() -> None:
    global ACTIVE
    ACTIVE = bool(flags.resource_attribution)


def set_enabled(on: bool) -> None:
    """Flip the recorders AND the thread-attribution plane together —
    they share the ``resource_attribution`` flag."""
    global ACTIVE
    ACTIVE = bool(on)
    trace.set_attribution_enabled(on)


_LOCK = threading.Lock()
_cap = int(flags.profiler_buffer_cap)
# sig -> program row (registry: one row per distinct compiled program;
# re-records update cost/compile fields in place).
_PROGRAMS: dict[str, dict] = {}
# Rows not yet drained into the device_programs table.
_NEW_PROGRAMS: "collections.deque[dict]" = collections.deque(maxlen=_cap)
_DISPATCHES: "collections.deque[dict]" = collections.deque(maxlen=_cap)
_HBM: "collections.deque[dict]" = collections.deque(maxlen=_cap)
# Residency pools that registered for forced flush-time sampling.
_POOLS: "weakref.WeakSet" = weakref.WeakSet()


def program_name(sig: str) -> str:
    """Stable short name for a program signature: the unit kind prefix
    plus a content hash — full fold signatures run to hundreds of chars
    and would bloat every dispatch row."""
    kind = sig.split("|", 1)[0] if "|" in sig else "program"
    import hashlib

    h = hashlib.blake2s(sig.encode(), digest_size=6).hexdigest()
    return f"{kind}:{h}"


def cost_analysis_of(compiled) -> dict:
    """(flops, bytes accessed) from a jax Compiled's XLA cost analysis —
    best-effort across jax versions (dict or [dict] returns, missing
    keys on some backends)."""
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        ca = dict(ca or {})
        return {
            "flops": float(ca.get("flops", 0.0) or 0.0),
            "bytes_accessed": float(ca.get("bytes accessed", 0.0) or 0.0),
        }
    except Exception:
        return {"flops": 0.0, "bytes_accessed": 0.0}


def record_program(
    sig: str,
    kind: Optional[str] = None,
    compile_s: float = 0.0,
    compiled: Any = None,
) -> None:
    """Register (or enrich) a compiled device program. Called at
    ``_get_program`` cache misses (kind + signature; cost unknown — the
    program is a traced jit, not yet an executable) and again when the
    background AOT worker produces a Compiled (cost analysis + measured
    compile seconds). Each (re-)record emits a row for the
    device_programs table so the series shows when costs became known."""
    if not ACTIVE:
        return
    row = {
        "time_ns": time.time_ns(),
        "program": program_name(sig),
        "kind": kind or (sig.split("|", 1)[0] if "|" in sig else "program"),
        "flops": 0.0,
        "bytes_accessed": 0.0,
        "compile_seconds": float(compile_s),
    }
    if compiled is not None:
        row.update(cost_analysis_of(compiled))
    with _LOCK:
        prev = _PROGRAMS.get(sig)
        if prev is not None:
            # Keep the richest view: an AOT record upgrades the
            # trace-time stub, never the reverse.
            row["flops"] = row["flops"] or prev["flops"]
            row["bytes_accessed"] = (
                row["bytes_accessed"] or prev["bytes_accessed"]
            )
            row["compile_seconds"] = (
                row["compile_seconds"] or prev["compile_seconds"]
            )
        _PROGRAMS[sig] = row
        _NEW_PROGRAMS.append(dict(row))


def record_dispatch(
    kind: str,
    duration_s: float,
    program: str = "",
    rows: int = 0,
    staged_bytes: int = 0,
    wire_bytes: int = 0,
) -> None:
    """One device dispatch, attributed to the ambient thread's
    (query_id, tenant, phase). ``staged_bytes`` is the decoded on-device
    footprint the dispatch covered; ``wire_bytes`` what actually crossed
    host->HBM (codec-compressed)."""
    if not ACTIVE:
        return
    attr = trace.current_attribution() or ("", "", "")
    with _LOCK:
        _DISPATCHES.append(
            {
                "time_ns": time.time_ns(),
                "query_id": attr[0],
                "tenant": attr[1],
                "phase": attr[2],
                "kind": kind,
                "program": program,
                "duration_ns": int(duration_s * 1e9),
                "rows": int(rows),
                "staged_bytes": int(staged_bytes),
                "wire_bytes": int(wire_bytes),
            }
        )


def record_hbm_rows(rows: list[dict]) -> None:
    """Buffer pre-built hbm_usage rows (serving/residency.py builds them
    under its own lock so the snapshot is consistent)."""
    if not ACTIVE or not rows:
        return
    with _LOCK:
        _HBM.extend(rows)


def register_pool(pool) -> None:
    """Track a ResidencyPool for forced sampling at telemetry-flush time
    (weakly — a dropped executor's pool just disappears)."""
    _POOLS.add(pool)


def sample_pools() -> None:
    """Force one usage snapshot from every registered pool (the flush
    path calls this so hbm_usage is fresh even on an idle pool)."""
    if not ACTIVE:
        return
    for pool in list(_POOLS):
        try:
            pool.sample_usage(force=True)
        except Exception:
            pass  # advisory; a sampling failure must never fail a flush


def program_cost(sig: str) -> Optional[dict]:
    """The registry row for a program signature (flops/bytes_accessed/
    compile_seconds), or None when never recorded — the r22 cost
    model's roofline prior reads cost_analysis through this instead of
    reaching into the private registry."""
    with _LOCK:
        row = _PROGRAMS.get(sig)
        return dict(row) if row is not None else None


# -- drains (single consumer per process: the self-telemetry flush) ----------
def drain_programs() -> list[dict]:
    with _LOCK:
        out = list(_NEW_PROGRAMS)
        _NEW_PROGRAMS.clear()
    return out


def drain_dispatches() -> list[dict]:
    with _LOCK:
        out = list(_DISPATCHES)
        _DISPATCHES.clear()
    return out


def drain_hbm() -> list[dict]:
    with _LOCK:
        out = list(_HBM)
        _HBM.clear()
    return out


def dispatches_snapshot() -> list[dict]:
    """Copies without draining (the soak harness peeks mid-run)."""
    with _LOCK:
        return [dict(d) for d in _DISPATCHES]


def buffered_counts() -> dict[str, int]:
    with _LOCK:
        return {
            "programs": len(_NEW_PROGRAMS),
            "dispatches": len(_DISPATCHES),
            "hbm": len(_HBM),
        }


def clear() -> None:
    """Drop all buffered rows and the program registry (tests)."""
    with _LOCK:
        _PROGRAMS.clear()
        _NEW_PROGRAMS.clear()
        _DISPATCHES.clear()
        _HBM.clear()


refresh()
