"""Host→HBM staging: table columns → device-sharded padded blocks.

The TPU analogue of the reference's per-PEM data locality: every device owns
a contiguous shard of the table's rows ([D, nblk, B] layout, sharded on the
leading device axis), padded to static shapes with a validity mask — XLA
requires static shapes, and padding+mask is how streaming row counts meet
that constraint (SURVEY.md §7 "Streaming/windowed execution vs XLA's static
shapes").

Strings never ship to HBM: their int32 dictionary codes do (table/column.py
write-side encoding), and group keys densify to gids host-side before
staging (ops/segment.py's contract).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from pixie_tpu.table.column import DictColumn
from pixie_tpu.table.table import Table
from pixie_tpu.utils import faults, flags, trace

# r22: the codec-vs-raw bar consults the learned cost model (lazily —
# serving's package init transitively imports the parallel package).
_COST_MODEL = None


def codec_min_ratio() -> float:
    """Effective ``staging_codec_min_ratio`` for every codec plan site:
    the flag, scaled by the cost model's measured codec-vs-raw staging
    byte rates when warm (clamped to the flag's rail band), or the flag
    exactly when the model is cold, shadowing, or disabled. Either lane
    decodes bit-identically — this bar moves only wire bytes."""
    global _COST_MODEL
    if _COST_MODEL is None:
        from pixie_tpu.serving import cost_model

        _COST_MODEL = cost_model
    if _COST_MODEL.ACTIVE:
        return _COST_MODEL.codec_min_ratio()
    return float(flags.staging_codec_min_ratio)

DEFAULT_BLOCK_ROWS = 1 << 17

# Cold-path phase timings (cumulative seconds since last reset): where a
# first query's latency goes — host column reads, gid densification,
# host-side pack, host→HBM transfer, program trace+compile+execute.
# bench.py resets before each cold query and writes the breakdown to the
# ledger (VERDICT r4 weakness 4).
COLD_PROFILE: dict[str, float] = {}


def reset_cold_profile() -> dict:
    snap = dict(COLD_PROFILE)
    COLD_PROFILE.clear()
    return snap


# Observed staged (decoded, HBM-resident) bytes per row, by table — the
# metadata admission control uses to estimate a query's staging cost
# BEFORE the cold stage starts (serving/admission.estimate_staging_bytes).
# Updated after every staging; survives cache eviction.
OBSERVED_BPR: dict[str, float] = {}


def record_observed_bpr(table_name: str, nbytes: int, rows: int) -> None:
    if table_name and rows > 0 and nbytes > 0:
        OBSERVED_BPR[table_name] = nbytes / rows


class timed:
    """with timed('stage'): ... — accumulates into COLD_PROFILE, and
    (r11) emits the same interval as a ``device.<key>`` trace span under
    the running query's ambient context, so cold-path phase timings stop
    being a bare dict and join the query's span tree."""

    def __init__(self, key: str):
        self.key = key

    def __enter__(self):
        self.t0 = time.perf_counter()

    def __exit__(self, *exc):
        dt = time.perf_counter() - self.t0
        COLD_PROFILE[self.key] = COLD_PROFILE.get(self.key, 0.0) + dt
        if trace.ACTIVE:
            trace.phase(f"device.{self.key}", dt)
        return False


@dataclasses.dataclass
class StagedColumns:
    """Columns resident on the mesh + the host-side key bookkeeping."""

    blocks: dict[str, jax.Array]  # name -> [D, nblk, B], device-sharded
    mask: jax.Array  # [D, nblk, B] bool, False on padding
    gids: Optional[jax.Array]  # [D, nblk, B] int32 (None: no grouping)
    num_rows: int
    num_devices: int
    block_rows: int
    num_groups: int
    capacity: int  # padded static group capacity (pow2)
    key_columns: list  # per group col: np.ndarray or DictColumn, gid order
    dictionaries: dict  # col name -> StringDictionary (for aux/LUT building)
    # Frame-of-reference narrowing: int64 columns whose value RANGE fits a
    # narrower dtype ship as uint8/int32 of (value - offset); the compiled
    # program widens per block (cast + add, VPU-cheap). Host→HBM transfer
    # is the cold-path bottleneck (~19MB/s through a tunneled chip, ~10GB/s
    # on local PCIe), so staged bytes are the metric that matters.
    narrow_offsets: dict = dataclasses.field(default_factory=dict)
    # Int-dictionary columns: blocks[name] holds SMALL-DOMAIN CODES
    # (uint8/uint16) and int_dicts[name] is the [C] int64 value LUT — the
    # cell lane aggregates per (group, code) histogram instead of per row.
    int_dicts: dict = dataclasses.field(default_factory=dict)


def _pow2_at_least(n: int, floor: int = 8) -> int:
    c = floor
    while c < n:
        c <<= 1
    return c


def bucket_block_count(n: int) -> int:
    """Round a per-device block count up to its signature bucket.

    Buckets are quarter-octave, pow2-scaled: within each octave
    (2^(k-1), 2^k] counts round up to multiples of 2^(k-3), i.e. the
    bucket set is {1..8, 10, 12, 14, 16, 20, 24, 28, 32, 40, ...}. That
    bounds shape variety to O(log) distinct block counts (so compiled
    programs and the persistent .jax_cache are shared across tables whose
    padded sizes land in the same bucket) at <= 25% padding waste — a
    strict pow2 bucket would cost up to 100% extra masked blocks, which
    at gigarow scale is real HBM and host->HBM transfer."""
    if n <= 8:
        return max(n, 1)
    step = 1 << ((n - 1).bit_length() - 3)
    return ((n + step - 1) // step) * step


def block_geometry(
    num_rows: int, d: int, block_rows: int
) -> tuple[int, int]:
    """(per-device block size b, blocks-per-device nblk) for a staging of
    ``num_rows`` over ``d`` devices. With ``signature_buckets`` the
    geometry derives from the pow2-padded row count and nblk rounds up to
    its bucket (padding rows are masked), so tables in the same bucket
    produce identical block shapes — and therefore share one compiled
    program in-process and one .jax_cache entry across processes."""
    if flags.signature_buckets:
        padded = _pow2_at_least(max(num_rows, 1), floor=1)
        b = min(block_rows, _pow2_at_least(max(padded // d, 1), floor=256))
        nblk = bucket_block_count(
            max((num_rows + d * b - 1) // (d * b), 1)
        )
    else:
        b = min(
            block_rows, _pow2_at_least(max(num_rows // d, 1), floor=256)
        )
        nblk = max((num_rows + d * b - 1) // (d * b), 1)
    return b, nblk


def read_columns(
    table: Table,
    columns: list[str],
    start_time: Optional[int] = None,
    stop_time: Optional[int] = None,
) -> tuple[dict[str, np.ndarray], int]:
    """Materialize needed columns via a cursor (host side). String columns
    come back as their int32 code arrays."""
    cols, n, _w, _nw = read_columns_windowed(
        table, columns, start_time, stop_time, want_windows=False
    )
    return cols, n


def read_columns_windowed(
    table: Table,
    columns: list[str],
    start_time: Optional[int] = None,
    stop_time: Optional[int] = None,
    want_windows: bool = True,
):
    """Like read_columns, plus per-row WINDOW ids derived from the
    cursor's end-of-window markers (a batch with eow=True closes the
    current window — the same boundaries the host AggNode emits on,
    exec/agg_node.py consume_next_impl). Returns
    (cols, n, window_ids|None, n_windows)."""
    batches = []
    cur = table.cursor(start_time, stop_time)
    while not cur.done():
        b = cur.next_batch()
        if b is None:
            break
        if b.num_rows or b.eow:
            batches.append(b)
    cols: dict[str, np.ndarray] = {}
    n = sum(b.num_rows for b in batches)
    for name in columns:
        parts = []
        for b in batches:
            if not b.num_rows:
                continue
            c = b.col(name)
            parts.append(c.codes if isinstance(c, DictColumn) else np.asarray(c))
        cols[name] = (
            np.concatenate(parts) if parts
            else np.empty(0, np.int32)
        )
    wids = None
    n_windows = 1
    if want_windows:
        parts = []
        w = 0
        for b in batches:
            if b.num_rows:
                parts.append(np.full(b.num_rows, w, np.int64))
            if b.eow:
                w += 1
        wids = (
            np.concatenate(parts) if parts else np.empty(0, np.int64)
        )
        # Rows after the last eow belong to a final (unclosed) window.
        n_windows = w + 1 if (not batches or not batches[-1].eow) else w
        n_windows = max(n_windows, 1)
    return cols, n, wids, n_windows


def int_dict_encode(
    arr: np.ndarray, max_card: int
) -> Optional[tuple[np.ndarray, np.ndarray]]:
    """(codes, sorted value LUT) when the column has <= max_card distinct
    values, else None. Costs one sample-unique + one searchsorted pass +
    one verify compare over the column — paid once per staging (cached
    with it). Telemetry int columns (status codes, ports, enum-ish ids)
    are routinely tiny-domain."""
    if arr.size == 0 or arr.dtype != np.int64 or max_card < 2:
        return None
    lut = np.unique(arr[: 1 << 16])
    if len(lut) > max_card:
        return None
    codes = np.searchsorted(lut, arr)
    codes = np.minimum(codes, len(lut) - 1)
    ok = lut[codes] == arr
    if not ok.all():
        extra = np.unique(arr[~ok])
        lut = np.unique(np.concatenate([lut, extra]))
        if len(lut) > max_card:
            return None
        codes = np.searchsorted(lut, arr)
    dtype = np.uint8 if len(lut) <= 256 else np.uint16
    return codes.astype(dtype), lut


def _narrow_int(arr: np.ndarray) -> tuple[np.ndarray, Optional[int]]:
    """Frame-of-reference narrowing for int columns: ship (value - min) as
    uint8/uint16 (or int32 for int64 inputs) when the RANGE fits, with the
    offset reconstructed on device (widened back to int64 per block).
    Applies to int64 values AND int32 dictionary codes — low-cardinality
    string columns (services, pods) ship at 1 byte/row, ports/status codes
    at 2. (None offset = as-is.) Host→HBM transfer is the cold-path
    bottleneck, so staged bytes are the metric that matters."""
    if arr.size == 0 or arr.dtype not in (np.int64, np.int32):
        return arr, None
    lo = int(arr.min())
    hi = int(arr.max())
    rng = hi - lo
    if rng <= 0xFF:
        return (arr - lo).astype(np.uint8), lo
    if rng <= 0xFFFF:
        return (arr - lo).astype(np.uint16), lo
    if arr.dtype == np.int64 and rng < (1 << 31):
        return (arr - lo).astype(np.int32), lo
    return arr, None


import functools


@functools.lru_cache(maxsize=64)
def _mask_builder(mesh: Mesh, d: int, nblk: int, b: int):
    """Jitted per (mesh, geometry) — a fresh jit per staging would pay a
    trace+compile each time; num_rows stays a traced argument so one
    compiled kernel serves every row count at this geometry."""
    axis_name = tuple(mesh.axis_names)  # dim0 over every mesh axis
    sharding = NamedSharding(mesh, P(axis_name))

    def make(n):
        idx = jax.lax.broadcasted_iota(jnp.int64, (d, nblk, b), 0) * (
            nblk * b
        ) + jax.lax.broadcasted_iota(jnp.int64, (d, nblk, b), 1) * b + (
            jax.lax.broadcasted_iota(jnp.int64, (d, nblk, b), 2)
        )
        return idx < n

    return jax.jit(make, out_shardings=sharding)


def _build_mask(mesh: Mesh, d: int, nblk: int, b: int, num_rows: int):
    """Validity mask computed ON the mesh (iota < num_rows): at 1 byte/row
    a transferred mask is a material slice of cold-path bytes."""
    return _mask_builder(mesh, d, nblk, b)(num_rows)


def stage_columns(
    mesh: Mesh,
    cols: dict[str, np.ndarray],
    num_rows: int,
    gids: Optional[np.ndarray] = None,
    num_groups: int = 1,
    key_columns: Optional[list] = None,
    dictionaries: Optional[dict] = None,
    block_rows: int = DEFAULT_BLOCK_ROWS,
    f32_cols: Optional[set] = None,
    int_dicts: Optional[dict] = None,
) -> StagedColumns:
    """Pad/reshape host columns into [D, nblk, B] and shard over the mesh.

    ``f32_cols`` names float64 columns consumed only by f32-state sketch
    UDAs (t-digest keeps f32 centroids): staging them as f32 halves their
    transfer with zero end-to-end precision change. ``int_dicts`` maps
    column names already replaced by small-domain codes (see
    int_dict_encode) to their value LUTs."""
    from pixie_tpu.ops import codec as _codec

    axis_name = tuple(mesh.axis_names)  # dim0 over every mesh axis
    d = mesh.devices.size
    b, nblk = block_geometry(num_rows, d, block_rows)
    total = d * nblk * b
    sharding = NamedSharding(mesh, P(axis_name))

    def flat_pad(arr, fill):
        out = np.full(total, fill, dtype=arr.dtype if arr.size else np.int32)
        out[:num_rows] = arr
        return out

    def shape3(arr, fill):
        return flat_pad(arr, fill).reshape(d, nblk, b)

    use_codec = flags.staging_codec
    narrow_offsets: dict[str, int] = {}
    blocks: dict[str, jax.Array] = {}
    for name, a in cols.items():
        with timed("stage_host_pack"):
            if f32_cols and name in f32_cols and a.dtype == np.float64:
                a = a.astype(np.float32)
            else:
                a, off = _narrow_int(a)
                if off is not None:
                    narrow_offsets[name] = off
            flat = flat_pad(a, 0)
        # Staging codec (r13): ship the packed representation encoded
        # when a lightweight encoder pays; a jitted program expands it
        # in HBM, bit-identical to the uncompressed transfer.
        payload = None
        if use_codec and num_rows > 0:
            with timed("stage_encode"):
                cplan = _codec.plan_codec_local(
                    flat, d, nblk, b, num_rows,
                    codec_min_ratio(),
                )
                if cplan is not None:
                    try:
                        payload = _codec.encode_window(flat, cplan, num_rows)
                    except _codec.CodecOverflow:
                        payload = None
        COLD_PROFILE["stage_bytes"] = COLD_PROFILE.get(
            "stage_bytes", 0.0
        ) + float(flat.nbytes)
        if payload is not None:
            with timed("stage_transfer"):
                args = _codec.put_payload(mesh, payload)
                COLD_PROFILE["wire_bytes"] = COLD_PROFILE.get(
                    "wire_bytes", 0.0
                ) + float(payload.nbytes)
            with timed("stage_decode"):
                blocks[name] = _codec.decoder(mesh, cplan, nblk, b)(*args)
        else:
            with timed("stage_transfer"):
                # device_put is async on local backends; do NOT block per
                # column — that serializes transfers behind each other and
                # behind the next column's host pack. One sync below, after
                # every put is in flight (the PJRT runtime retains the host
                # buffer until its transfer completes).
                blocks[name] = jax.device_put(
                    flat.reshape(d, nblk, b), sharding
                )
                COLD_PROFILE["wire_bytes"] = COLD_PROFILE.get(
                    "wire_bytes", 0.0
                ) + float(flat.nbytes)
    with timed("stage_transfer"):
        if blocks:
            jax.block_until_ready(list(blocks.values()))
    mask_dev = _build_mask(mesh, d, nblk, b, num_rows)
    gids_dev = None
    if gids is not None:
        gflat = flat_pad(_narrow_gids(gids, num_groups), 0)
        gpayload = None
        if use_codec and num_rows > 0:
            # r16: the gids lane rides the codec like any value column —
            # sorted/low-churn group keys RLE to ~nothing.
            with timed("stage_encode"):
                gplan = _codec.plan_codec_local(
                    gflat, d, nblk, b, num_rows,
                    codec_min_ratio(),
                )
                if gplan is not None:
                    try:
                        gpayload = _codec.encode_window(
                            gflat, gplan, num_rows
                        )
                    except _codec.CodecOverflow:
                        gpayload = None
        if gpayload is not None:
            with timed("stage_transfer"):
                gargs = _codec.put_payload(mesh, gpayload)
                COLD_PROFILE["wire_bytes"] = COLD_PROFILE.get(
                    "wire_bytes", 0.0
                ) + float(gpayload.nbytes)
            with timed("stage_decode"):
                gids_dev = _codec.decoder(mesh, gplan, nblk, b)(*gargs)
        else:
            gids_dev = jax.device_put(
                gflat.reshape(d, nblk, b), sharding
            )
    return StagedColumns(
        blocks=blocks,
        mask=mask_dev,
        gids=gids_dev,
        num_rows=num_rows,
        num_devices=d,
        block_rows=b,
        num_groups=num_groups,
        capacity=_pow2_at_least(max(num_groups, 1)),
        key_columns=list(key_columns or []),
        dictionaries=dict(dictionaries or {}),
        narrow_offsets=narrow_offsets,
        int_dicts=dict(int_dicts or {}),
    )


def repartition_staged(mesh: Mesh, staged: StagedColumns) -> StagedColumns:
    """Re-place one staged table onto ``mesh`` (r23 geometry recovery).

    Every rung of the degradation ladder keeps the total device count
    (losing a host is a trust statement about the ``hosts`` axis, not a
    removal of local silicon), so the [D, nblk, B] shapes are unchanged
    and the move is a pure ``device_put`` resolved through the SAME
    partition-rule tree that placed the shards originally: blocks, mask,
    and gids shard dim 0 over the new axis tuple; values bit-identical.
    Host-side key bookkeeping carries over untouched."""
    from pixie_tpu.distributed import mesh as mesh_lib

    names = [f"blocks/{n}" for n in staged.blocks] + ["mask"]
    if staged.gids is not None:
        names.append("gids")
    sh = mesh_lib.match_partition_rules(
        mesh_lib.STAGED_PARTITION_RULES, names, mesh
    )
    return dataclasses.replace(
        staged,
        blocks={
            n: jax.device_put(a, sh[f"blocks/{n}"])
            for n, a in staged.blocks.items()
        },
        mask=jax.device_put(staged.mask, sh["mask"]),
        gids=(
            jax.device_put(staged.gids, sh["gids"])
            if staged.gids is not None
            else None
        ),
    )


def _narrow_gids(gids: np.ndarray, num_groups: int) -> np.ndarray:
    """Dense gids ship u8/u16 when the group count fits (the compiled
    programs cast to int32 per block anyway)."""
    if num_groups <= 0xFF + 1:
        return gids.astype(np.uint8)
    if num_groups <= 0xFFFF + 1:
        return gids.astype(np.uint16)
    return gids.astype(np.int32)


@functools.lru_cache(maxsize=64)
def _shard_mask_builder(mesh: Mesh, d: int, nblk: int, b: int, region: int):
    """Per-shard validity mask for partitioned stagings: each hosts-axis
    shard owns a contiguous ``region`` of the flat row space, valid up
    to its own row count (tail-padding WITHIN each region, unlike the
    single global tail _mask_builder models). Jitted per geometry; the
    [H] counts vector stays a traced argument."""
    axis_name = tuple(mesh.axis_names)  # dim0 over every mesh axis
    sharding = NamedSharding(mesh, P(axis_name))

    def make(counts):
        idx = jax.lax.broadcasted_iota(jnp.int64, (d, nblk, b), 0) * (
            nblk * b
        ) + jax.lax.broadcasted_iota(jnp.int64, (d, nblk, b), 1) * b + (
            jax.lax.broadcasted_iota(jnp.int64, (d, nblk, b), 2)
        )
        return (idx % region) < counts[idx // region]

    return jax.jit(make, out_shardings=sharding)


def stage_partitioned(
    mesh: Mesh,
    cols: dict[str, np.ndarray],
    gids: np.ndarray,
    shard_rows: np.ndarray,
    num_groups: int,
    block_rows: int = DEFAULT_BLOCK_ROWS,
) -> StagedColumns:
    """Stage shard-major host columns so each hosts-axis shard owns a
    contiguous region of devices (the r21 distributed join's layout).

    ``cols``/``gids`` arrive ALREADY permuted shard-major (rows of
    shard h contiguous, original order preserved within a shard) with
    ``shard_rows[h]`` rows per shard. Geometry is per-host: every host
    gets the block_geometry of the LARGEST shard over its ``d/H``
    devices, so regions are uniform (one compiled program) and ragged
    shards tail-pad within their own region — the per-shard mask comes
    from _shard_mask_builder, not the global-tail mask. Narrowing
    matches stage_columns (one frame-of-reference offset per column
    over the whole permuted array); the staging codec is not applied
    on this path (shard regions break the contiguous-rows assumption
    of the window codec plans — revisit if transfer dominates)."""
    axis_name = tuple(mesh.axis_names)  # dim0 over every mesh axis
    H = int(mesh.devices.shape[0])
    d = mesh.devices.size
    d_host = d // H
    shard_rows = np.asarray(shard_rows, np.int64)
    assert shard_rows.shape == (H,) and int(shard_rows.sum()) == len(gids)
    b, nblk = block_geometry(int(max(shard_rows.max(), 1)), d_host, block_rows)
    region = d_host * nblk * b
    total = d * nblk * b
    offs = np.concatenate([[0], np.cumsum(shard_rows)[:-1]])
    sharding = NamedSharding(mesh, P(axis_name))

    def scatter(arr, fill):
        out = np.full(total, fill, dtype=arr.dtype if arr.size else np.int32)
        for h in range(H):
            r = int(shard_rows[h])
            out[h * region : h * region + r] = arr[offs[h] : offs[h] + r]
        return out

    narrow_offsets: dict[str, int] = {}
    blocks: dict[str, jax.Array] = {}
    for name, a in cols.items():
        with timed("stage_host_pack"):
            a, off = _narrow_int(np.asarray(a))
            if off is not None:
                narrow_offsets[name] = off
            flat = scatter(a, 0)
        COLD_PROFILE["stage_bytes"] = COLD_PROFILE.get(
            "stage_bytes", 0.0
        ) + float(flat.nbytes)
        with timed("stage_transfer"):
            blocks[name] = jax.device_put(flat.reshape(d, nblk, b), sharding)
            COLD_PROFILE["wire_bytes"] = COLD_PROFILE.get(
                "wire_bytes", 0.0
            ) + float(flat.nbytes)
    gflat = scatter(_narrow_gids(np.asarray(gids), num_groups), 0)
    gids_dev = jax.device_put(gflat.reshape(d, nblk, b), sharding)
    with timed("stage_transfer"):
        jax.block_until_ready(list(blocks.values()) + [gids_dev])
    mask_dev = _shard_mask_builder(mesh, d, nblk, b, region)(
        jnp.asarray(shard_rows)
    )
    return StagedColumns(
        blocks=blocks,
        mask=mask_dev,
        gids=gids_dev,
        num_rows=int(shard_rows.sum()),
        num_devices=d,
        block_rows=b,
        num_groups=num_groups,
        capacity=_pow2_at_least(max(num_groups, 1)),
        key_columns=[],
        dictionaries={},
        narrow_offsets=narrow_offsets,
        int_dicts={},
    )


# -- streaming, double-buffered staging (the r6 cold-path pipeline) ----------
#
# The monolithic path above materializes the WHOLE table in HBM before the
# first FLOP; at bench scale the cold query is therefore ≈ pack + transfer +
# compute in sequence. The streaming path splits the table into fixed-size
# row windows and runs a three-stage software pipeline: window k+2 is
# host-packed on a background thread, window k+1 is in flight via async
# jax.device_put, and window k is being folded on the mesh — end-to-end
# time becomes ≈ max(pack, transfer, compute) + one window of fill/drain.
# Every window shares one pack recipe (dtypes/offsets/LUTs fixed from the
# FULL columns) so a single compiled fold program serves them all.


@dataclasses.dataclass
class StreamPlan:
    """Per-column pack recipe + window geometry, fixed across windows.

    col_plans[name] is one of ("raw", None), ("f32", None),
    ("narrow", (np_dtype, offset)), ("intdict", (lut, np_dtype)). The
    recipe is derived from the FULL host columns once, so every window's
    blocks share dtypes and shapes — required for one compiled fold
    program to serve all windows, and for the post-stream concatenation
    to be a valid monolithic staging."""

    col_plans: dict
    narrow_offsets: dict  # name -> int offset (frame-of-reference)
    int_dicts: dict  # name -> [C] int64 value LUT
    block_dtypes: dict  # name -> np.dtype of the staged blocks
    window_rows: int
    num_rows: int
    n_windows: int
    d: int
    nblk: int  # blocks per window per device
    b: int
    gid_dtype: Optional[np.dtype]
    num_groups: int
    # Staging codec (r13): name -> ops.codec.CodecPlan for columns whose
    # wire bytes an encoder beats by >= staging_codec_min_ratio. Fixed
    # from the FULL column like every other recipe entry, so all windows
    # share one decode program. Columns absent here ship passthrough.
    codecs: dict = dataclasses.field(default_factory=dict)
    # r16: the GIDS stream rides the codec too — rows grouped by sorted
    # or low-churn keys yield long gid runs that RLE to ~nothing, and
    # the gids lane is a full extra column of wire bytes on every
    # host-gids staging. None = passthrough (random-ish gids).
    gid_codec: Optional[object] = None

    def window_block_nbytes(self) -> int:
        """Decoded (HBM) bytes per full window: column blocks only —
        what stage_bytes accounts per window (gids ride separately)."""
        return sum(
            self.d * self.nblk * self.b * np.dtype(dt).itemsize
            for dt in self.block_dtypes.values()
        )


def int_dict_lut(arr: np.ndarray, max_card: int) -> Optional[np.ndarray]:
    """LUT-only variant of int_dict_encode: the sorted value LUT when the
    column's FULL value set fits max_card, else None. Verified over the
    whole column, so per-window searchsorted encodes against it are exact
    (the per-window encode is what rides the background pack thread)."""
    enc = int_dict_encode(arr, max_card)
    return None if enc is None else enc[1]


def _narrow_int_plan(arr: np.ndarray) -> tuple[np.dtype, Optional[int]]:
    """_narrow_int's decision without the conversion: (dtype, offset) —
    offset None means ship as-is. Computed once over the full column so
    every window narrows identically (stable block dtypes)."""
    if arr.size == 0 or arr.dtype not in (np.int64, np.int32):
        return arr.dtype, None
    lo = int(arr.min())
    rng = int(arr.max()) - lo
    if rng <= 0xFF:
        return np.dtype(np.uint8), lo
    if rng <= 0xFFFF:
        return np.dtype(np.uint16), lo
    if arr.dtype == np.int64 and rng < (1 << 31):
        return np.dtype(np.int32), lo
    return arr.dtype, None


def plan_stream(
    mesh: Mesh,
    cols: dict[str, np.ndarray],
    num_rows: int,
    window_rows: int,
    block_rows: int = DEFAULT_BLOCK_ROWS,
    f32_cols: Optional[set] = None,
    cell_cols: Optional[dict] = None,
    num_groups: int = 1,
    has_gids: bool = False,
    gids: Optional[np.ndarray] = None,
) -> StreamPlan:
    """Fix the pack recipe + window geometry for a streamed staging.

    window_rows is clamped to the table so a small table (or a huge
    window flag) degenerates to ONE window whose geometry matches what
    stage_columns would have chosen — the fold then reproduces the
    monolithic scan bit-for-bit. With ``signature_buckets`` the clamp is
    to the POW2-PADDED row count, so every small table whose padded size
    lands in the same bucket shares one window geometry — and one
    compiled fold executable."""
    d = mesh.devices.size
    clamp = max(num_rows, 1)
    if flags.signature_buckets:
        clamp = _pow2_at_least(clamp, floor=1)
    window_rows = max(min(int(window_rows), clamp), 1)
    n_windows = max((num_rows + window_rows - 1) // window_rows, 1)
    b, nblk = block_geometry(window_rows, d, block_rows)
    col_plans: dict = {}
    narrow_offsets: dict = {}
    int_dicts: dict = {}
    block_dtypes: dict = {}
    for name, a in cols.items():
        if cell_cols and name in cell_cols:
            lut = int_dict_lut(a, cell_cols[name])
            if lut is not None:
                dt = np.dtype(np.uint8 if len(lut) <= 256 else np.uint16)
                col_plans[name] = ("intdict", (lut, dt))
                int_dicts[name] = lut
                block_dtypes[name] = dt
                continue
        if f32_cols and name in f32_cols and a.dtype == np.float64:
            col_plans[name] = ("f32", None)
            block_dtypes[name] = np.dtype(np.float32)
            continue
        dt, off = _narrow_int_plan(a)
        if off is not None:
            col_plans[name] = ("narrow", (dt, off))
            narrow_offsets[name] = off
            block_dtypes[name] = dt
        else:
            col_plans[name] = ("raw", None)
            block_dtypes[name] = (
                np.dtype(a.dtype) if a.size else np.dtype(np.int32)
            )
    gid_dtype = None
    if has_gids:
        gid_dtype = np.dtype(
            np.uint8
            if num_groups <= 0xFF + 1
            else (np.uint16 if num_groups <= 0xFFFF + 1 else np.int32)
        )
    # Staging codec (r13): pick a per-column encoder from the FULL
    # column's stats so every window encodes identically (one decode
    # program serves all windows, and the decoded blocks are exactly
    # what the passthrough pack would have transferred). Delta needs a
    # diff-preserving (raw/narrow int) transform; RLE composes with
    # anything because run boundaries are invariant under the pack
    # transforms (bit-pattern changes map 1:1).
    codecs: dict = {}
    gid_codec = None
    if flags.staging_codec:
        from pixie_tpu.ops import codec as _codec

        for name, a in cols.items():
            kind = col_plans[name][0]
            bdt = np.dtype(block_dtypes[name])
            affine = kind in ("raw", "narrow") and bdt.kind in "iu"
            cp = _codec.plan_codec(
                a, bdt, d, nblk, b, window_rows, num_rows,
                codec_min_ratio(), affine,
            )
            if cp is not None:
                codecs[name] = cp
        if gids is not None and gid_dtype is not None and gids.size:
            # r16: the gids lane is an extra full-width column on every
            # host-gids staging; sorted/low-churn group keys make it
            # run-heavy, so plan it like any value column. The narrow
            # cast (astype, values unchanged) preserves both run
            # boundaries and diffs, so stats on the raw gids are exact.
            gid_codec = _codec.plan_codec(
                gids, gid_dtype, d, nblk, b, window_rows, num_rows,
                codec_min_ratio(), affine=True,
            )
    return StreamPlan(
        col_plans=col_plans,
        narrow_offsets=narrow_offsets,
        int_dicts=int_dicts,
        block_dtypes=block_dtypes,
        window_rows=window_rows,
        num_rows=num_rows,
        n_windows=n_windows,
        d=d,
        nblk=nblk,
        b=b,
        gid_dtype=gid_dtype,
        num_groups=num_groups,
        codecs=codecs,
        gid_codec=gid_codec,
    )


def pack_stream_window(
    plan: StreamPlan,
    cols: dict[str, np.ndarray],
    gids: Optional[np.ndarray],
    w: int,
    skip_cols: bool = False,
):
    """Host-pack window w per the plan: narrow/f32/int-dict encode + pad +
    reshape to [D, nblk, B]. Runs on the streaming pipeline's background
    thread — this is the 'pack' stage that overlaps transfer and compute.
    Returns (rows, packed_cols, packed_gids, wire_nbytes): with the
    staging codec on, a packed_cols value may be a CodecPayload (the
    compressed representation the wire actually carries — the device
    decode expands it to the identical block), and wire_nbytes counts
    what ships, not what lands. ``skip_cols`` packs only the gids — the
    resident-ingest path, where the window's columns are already in
    HBM and only the query-specific group ids must travel."""
    from pixie_tpu.ops import codec as _codec

    # Fault site: a poisoned stream pack (chaos tests prove the query
    # falls back to monolithic staging, still on-device, and stays
    # correct — MeshExecutor.stream_fallback_errors records it).
    if faults.ACTIVE:
        faults.check("staging.pack")
    with timed("stage_stream_pack"):
        lo = w * plan.window_rows
        hi = min(lo + plan.window_rows, plan.num_rows)
        rows = hi - lo
        total = plan.d * plan.nblk * plan.b

        def flat_pad(a, dtype):
            # np.empty + tail-zero, not np.zeros: the rows prefix is about
            # to be overwritten anyway, and this pack is on the pipeline's
            # critical path when pack is the slowest stage.
            out = np.empty(total, dtype=dtype)
            out[:rows] = a
            if rows < total:
                out[rows:] = 0
            return out

        def shape3(a, dtype):
            return flat_pad(a, dtype).reshape(plan.d, plan.nblk, plan.b)

        packed: dict = {}
        nbytes = 0
        for name, arr in ({} if skip_cols else cols).items():
            a = arr[lo:hi]
            kind, info = plan.col_plans[name]
            if kind == "f32":
                a = a.astype(np.float32)
            elif kind == "narrow":
                dt, off = info
                a = (a - off).astype(dt)
            elif kind == "intdict":
                lut, dt = info
                c = np.searchsorted(lut, a)
                a = np.minimum(c, len(lut) - 1).astype(dt)
            cp = plan.codecs.get(name)
            if cp is not None:
                flat = flat_pad(a, plan.block_dtypes[name])
                try:
                    with timed("stage_encode"):
                        packed[name] = _codec.encode_window(flat, cp, rows)
                    nbytes += packed[name].nbytes
                    continue
                except _codec.CodecOverflow:
                    # A window that defeats the plan ships raw —
                    # correctness never rides the plan's guess.
                    packed[name] = flat.reshape(
                        plan.d, plan.nblk, plan.b
                    )
                    nbytes += packed[name].nbytes
                    continue
            packed[name] = shape3(a, plan.block_dtypes[name])
            nbytes += packed[name].nbytes
        packed_gids = None
        if gids is not None:
            if plan.gid_codec is not None:
                flat = flat_pad(
                    gids[lo:hi].astype(plan.gid_dtype), plan.gid_dtype
                )
                try:
                    with timed("stage_encode"):
                        packed_gids = _codec.encode_window(
                            flat, plan.gid_codec, rows
                        )
                except _codec.CodecOverflow:
                    packed_gids = flat.reshape(
                        plan.d, plan.nblk, plan.b
                    )
            else:
                packed_gids = shape3(
                    gids[lo:hi].astype(plan.gid_dtype), plan.gid_dtype
                )
            nbytes += packed_gids.nbytes
        return rows, packed, packed_gids, nbytes


def put_window_gids(mesh: Mesh, pgids, nblk: int, b: int):
    """Land one window's packed gids on the mesh: a raw [D, nblk, B]
    ndarray device_puts as before; a CodecPayload (r16 gid codec)
    transfers the compressed representation and expands on device —
    bit-identical to the raw put."""
    from pixie_tpu.ops import codec as _codec

    if pgids is None:
        return None
    axis_name = tuple(mesh.axis_names)  # dim0 over every mesh axis
    if isinstance(pgids, _codec.CodecPayload):
        args = _codec.put_payload(mesh, pgids)
        return _codec.decoder(mesh, pgids.plan, nblk, b)(*args)
    return jax.device_put(pgids, NamedSharding(mesh, P(axis_name)))


def staged_gid_nbytes(pgids) -> int:
    """Decoded (HBM) bytes a packed-gids value lands as — the
    stage_bytes accounting view; .nbytes on a CodecPayload is WIRE
    bytes."""
    from pixie_tpu.ops import codec as _codec

    if pgids is None:
        return 0
    if isinstance(pgids, _codec.CodecPayload):
        return pgids.plan.block_nbytes()
    return int(pgids.nbytes)


@functools.lru_cache(maxsize=16)
def _concat_builder(mesh: Mesh, n_parts: int):
    """Jitted device-side concatenation along the block axis, sharding
    preserved (device-local copies; no collective). Used to assemble the
    streamed windows into one monolithic StagedColumns for the warm-path
    HBM cache."""
    axis_name = tuple(mesh.axis_names)  # dim0 over every mesh axis
    sharding = NamedSharding(mesh, P(axis_name))
    return jax.jit(
        lambda *xs: jnp.concatenate(xs, axis=1), out_shardings=sharding
    )


@functools.lru_cache(maxsize=64)
def _zeros_builder(mesh: Mesh, d: int, nblk: int, b: int, dtype_str: str):
    """Device-allocated zero blocks (sharded, NO host transfer): the
    bucket padding appended to a concatenated stream staging. Padding
    blocks are fully masked, so the warm program scans them as no-ops."""
    axis_name = tuple(mesh.axis_names)  # dim0 over every mesh axis
    sharding = NamedSharding(mesh, P(axis_name))
    return jax.jit(
        lambda: jnp.zeros((d, nblk, b), np.dtype(dtype_str)),
        out_shardings=sharding,
    )


def concat_stream_windows(
    mesh: Mesh,
    plan: StreamPlan,
    win_blocks: list,
    win_masks: list,
    win_gids: list,
    key_plan_num_groups: int,
    key_columns: list,
    dictionaries: dict,
) -> StagedColumns:
    """Assemble per-window device blocks into one StagedColumns so warm
    queries hit HBM directly (same contract as stage_columns; the row
    layout is per-window-packed, which the per-window masks encode).
    With ``signature_buckets`` the concatenated block count is padded up
    to its bucket with device-allocated zero blocks (masked, never
    transferred) so the warm program's shapes — and its compiled
    executable + .jax_cache entry — are shared across tables whose
    window counts land in the same bucket."""
    n_windows = len(win_masks)
    total_nblk = n_windows * plan.nblk
    pad_nblk = 0
    if flags.signature_buckets:
        pad_nblk = bucket_block_count(total_nblk) - total_nblk
    if n_windows == 1 and pad_nblk == 0:
        blocks = dict(win_blocks[0])
        mask = win_masks[0]
        gids = win_gids[0]
    else:
        n_parts = n_windows + (1 if pad_nblk else 0)
        cat = _concat_builder(mesh, n_parts)

        def pad(dtype):
            return _zeros_builder(
                mesh, plan.d, pad_nblk, plan.b, np.dtype(dtype).str
            )()

        def cat_padded(parts, dtype):
            if pad_nblk:
                parts = list(parts) + [pad(dtype)]
            return parts[0] if len(parts) == 1 else cat(*parts)

        blocks = {
            name: cat_padded(
                [wb[name] for wb in win_blocks], plan.block_dtypes[name]
            )
            for name in win_blocks[0]
        }
        mask = cat_padded(list(win_masks), np.bool_)
        gids = (
            cat_padded(list(win_gids), plan.gid_dtype)
            if win_gids and win_gids[0] is not None
            else None
        )
    return StagedColumns(
        blocks=blocks,
        mask=mask,
        gids=gids,
        num_rows=plan.num_rows,
        num_devices=plan.d,
        block_rows=plan.b,
        num_groups=max(key_plan_num_groups, 1),
        capacity=_pow2_at_least(max(key_plan_num_groups, 1)),
        key_columns=list(key_columns or []),
        dictionaries=dict(dictionaries or {}),
        narrow_offsets=dict(plan.narrow_offsets),
        int_dicts=dict(plan.int_dicts),
    )
