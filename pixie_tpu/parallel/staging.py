"""Host→HBM staging: table columns → device-sharded padded blocks.

The TPU analogue of the reference's per-PEM data locality: every device owns
a contiguous shard of the table's rows ([D, nblk, B] layout, sharded on the
leading device axis), padded to static shapes with a validity mask — XLA
requires static shapes, and padding+mask is how streaming row counts meet
that constraint (SURVEY.md §7 "Streaming/windowed execution vs XLA's static
shapes").

Strings never ship to HBM: their int32 dictionary codes do (table/column.py
write-side encoding), and group keys densify to gids host-side before
staging (ops/segment.py's contract).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from pixie_tpu.table.column import DictColumn
from pixie_tpu.table.table import Table

DEFAULT_BLOCK_ROWS = 1 << 17


@dataclasses.dataclass
class StagedColumns:
    """Columns resident on the mesh + the host-side key bookkeeping."""

    blocks: dict[str, jax.Array]  # name -> [D, nblk, B], device-sharded
    mask: jax.Array  # [D, nblk, B] bool, False on padding
    gids: Optional[jax.Array]  # [D, nblk, B] int32 (None: no grouping)
    num_rows: int
    num_devices: int
    block_rows: int
    num_groups: int
    capacity: int  # padded static group capacity (pow2)
    key_columns: list  # per group col: np.ndarray or DictColumn, gid order
    dictionaries: dict  # col name -> StringDictionary (for aux/LUT building)


def _pow2_at_least(n: int, floor: int = 8) -> int:
    c = floor
    while c < n:
        c <<= 1
    return c


def read_columns(
    table: Table,
    columns: list[str],
    start_time: Optional[int] = None,
    stop_time: Optional[int] = None,
) -> tuple[dict[str, np.ndarray], int]:
    """Materialize needed columns via a cursor (host side). String columns
    come back as their int32 code arrays."""
    batches = []
    cur = table.cursor(start_time, stop_time)
    while not cur.done():
        b = cur.next_batch()
        if b is None:
            break
        if b.num_rows:
            batches.append(b)
    cols: dict[str, np.ndarray] = {}
    n = sum(b.num_rows for b in batches)
    for name in columns:
        parts = []
        for b in batches:
            c = b.col(name)
            parts.append(c.codes if isinstance(c, DictColumn) else np.asarray(c))
        cols[name] = (
            np.concatenate(parts) if parts
            else np.empty(0, np.int32)
        )
    return cols, n


def stage_columns(
    mesh: Mesh,
    cols: dict[str, np.ndarray],
    num_rows: int,
    gids: Optional[np.ndarray] = None,
    num_groups: int = 1,
    key_columns: Optional[list] = None,
    dictionaries: Optional[dict] = None,
    block_rows: int = DEFAULT_BLOCK_ROWS,
) -> StagedColumns:
    """Pad/reshape host columns into [D, nblk, B] and shard over the mesh."""
    (axis_name,) = mesh.axis_names
    d = mesh.devices.size
    b = min(block_rows, _pow2_at_least(max(num_rows // d, 1), floor=256))
    nblk = max((num_rows + d * b - 1) // (d * b), 1)
    total = d * nblk * b
    sharding = NamedSharding(mesh, P(axis_name))

    def shape3(arr, fill):
        out = np.full(total, fill, dtype=arr.dtype if arr.size else np.int32)
        out[:num_rows] = arr
        return out.reshape(d, nblk, b)

    mask = np.zeros(total, dtype=bool)
    mask[:num_rows] = True
    blocks = {
        name: jax.device_put(shape3(a, 0), sharding) for name, a in cols.items()
    }
    mask_dev = jax.device_put(mask.reshape(d, nblk, b), sharding)
    gids_dev = (
        jax.device_put(shape3(gids.astype(np.int32), 0), sharding)
        if gids is not None
        else None
    )
    return StagedColumns(
        blocks=blocks,
        mask=mask_dev,
        gids=gids_dev,
        num_rows=num_rows,
        num_devices=d,
        block_rows=b,
        num_groups=num_groups,
        capacity=_pow2_at_least(max(num_groups, 1)),
        key_columns=list(key_columns or []),
        dictionaries=dict(dictionaries or {}),
    )
