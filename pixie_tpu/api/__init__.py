"""Client API (ref: src/api/python/pxapi/)."""

from pixie_tpu.api.client import Client, Conn, Row, ScriptExecutor

__all__ = ["Client", "Conn", "Row", "ScriptExecutor"]
