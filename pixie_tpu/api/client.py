"""pxapi-style client.

Ref: src/api/python/pxapi/client.py:100 (Client), :154 (ScriptExecutor) —
connect to a cluster, prepare a script, subscribe to result tables, stream
rows. The reference speaks gRPC to the cloud/vizier; here a Conn wraps
either an in-process QueryBroker (a vizier cluster) or a bare Carnot
engine, and the streaming surface is the same: per-table row iterators fed
as batches arrive.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Iterator, Optional


class Row:
    """One result row (ref: pxapi data.Row — column access by name)."""

    def __init__(self, relation, values: tuple):
        self._names = relation.col_names()
        self._values = values

    def __getitem__(self, key):
        if isinstance(key, int):
            return self._values[key]
        return self._values[self._names.index(key)]

    def keys(self):
        return list(self._names)

    def __repr__(self):
        return (
            "Row("
            + ", ".join(f"{n}={v!r}" for n, v in zip(self._names, self._values))
            + ")"
        )


class _TableSub:
    """Iterator over one output table's rows."""

    def __init__(self, name: str):
        self.name = name
        self._batches: list = []
        self._done = False
        self._cv = threading.Condition()

    def _push(self, batch) -> None:
        with self._cv:
            self._batches.append(batch)
            if batch.eos:
                self._done = True
            self._cv.notify()

    def _finish(self) -> None:
        with self._cv:
            self._done = True
            self._cv.notify()

    def __iter__(self) -> Iterator[Row]:
        i = 0
        while True:
            with self._cv:
                while i >= len(self._batches) and not self._done:
                    self._cv.wait(timeout=0.1)
                if i >= len(self._batches):
                    return
                batch = self._batches[i]
                i += 1
            d = batch.to_pydict()
            names = batch.relation.col_names()
            for row in zip(*(d[n] for n in names)):
                yield Row(batch.relation, row)


class ScriptExecutor:
    """Prepared script + table subscriptions (pxapi client.py:154)."""

    def __init__(self, conn: "Conn", pxl: str, args: Optional[dict] = None):
        self._conn = conn
        self._pxl = pxl
        self._args = args
        self._subs: dict[str, _TableSub] = {}
        self._callbacks: list[tuple[str, Callable]] = []
        self._ran = False

    def subscribe(self, table_name: str) -> _TableSub:
        if self._ran and table_name not in self._subs:
            # Batches were already routed to the subs that existed at
            # run(); a fresh sub would wait forever on data that will
            # never arrive.
            raise RuntimeError(
                "subscribe() after run(); subscribe before running or use "
                "results()"
            )
        sub = self._subs.setdefault(table_name, _TableSub(table_name))
        return sub

    def add_callback(self, table_name: str, fn: Callable[[Row], None]) -> None:
        self._callbacks.append((table_name, fn))

    def results(self, table_name: str) -> Iterator[Row]:
        """Run (if needed) and iterate one table's rows (pxapi shorthand)."""
        sub = self.subscribe(table_name)
        self.run()
        return iter(sub)

    def run(self) -> None:
        if self._ran:
            return
        self._ran = True
        result = self._conn._execute(self._pxl, self._args)
        for name, batches in result.tables.items():
            sub = self._subs.get(name)
            for b in batches:
                if sub is not None:
                    sub._push(b)
                for cb_name, fn in self._callbacks:
                    if cb_name == name:
                        d = b.to_pydict()
                        names = b.relation.col_names()
                        for row in zip(*(d[n] for n in names)):
                            fn(Row(b.relation, row))
        for sub in self._subs.values():
            sub._finish()
        self.tables = sorted(result.tables)


class Conn:
    """A connection to one cluster (pxapi client.py Conn)."""

    def __init__(self, broker=None, carnot=None, name: str = "local"):
        if (broker is None) == (carnot is None):
            raise ValueError("pass exactly one of broker=, carnot=")
        self._broker = broker
        self._carnot = carnot
        self.name = name

    def prepare_script(
        self, pxl: str, args: Optional[dict] = None
    ) -> ScriptExecutor:
        return ScriptExecutor(self, pxl, args)

    def run_script(self, name: str, args: Optional[dict] = None):
        """Run a bundled library script by name; returns the QueryResult."""
        from pixie_tpu.scripts.library import ScriptLibrary

        lib = ScriptLibrary()
        script = lib.load(name)
        return self._execute(
            script.pxl, None, exec_funcs=script.exec_funcs(args)
        )

    def _execute(self, pxl: str, args, exec_funcs=None):
        if self._broker is not None:
            return self._broker.execute_script(
                pxl, script_args=args, exec_funcs=exec_funcs
            )
        return self._carnot.execute_query(
            pxl, script_args=args, exec_funcs=exec_funcs
        )


class Client:
    """Entry point (pxapi client.py:100). The reference authenticates
    against the cloud and lists viziers; in-process there is one 'cluster'
    per broker/engine handed to connect()."""

    def __init__(self):
        self._conns: dict[str, Conn] = {}

    def connect_to_cluster(self, cluster, name: str = "local") -> Conn:
        from pixie_tpu.engine import Carnot

        if isinstance(cluster, Carnot):
            conn = Conn(carnot=cluster, name=name)
        else:
            conn = Conn(broker=cluster, name=name)
        self._conns[name] = conn
        return conn

    def list_healthy_clusters(self) -> list[str]:
        return sorted(self._conns)
