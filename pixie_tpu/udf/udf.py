"""UDF/UDA/UDTF base classes.

Ref: src/carnot/udf/udf.h — ScalarUDF::Exec (:78), UDA::Update/Merge/Finalize
with optional Serialize/DeSerialize for partial aggregates (:91-104). The
reference executes row-at-a-time through virtual calls and wraps that in a
column loop (udf_wrapper.h); here the column IS the unit: a scalar UDF is a
function over whole device arrays (jit-fusable into its consumers), and a UDA
state is a pytree of fixed-shape tensors with a leading num_groups axis.

Partial aggregation (the PEM->Kelvin split, partial_op_mgr.h:94) maps to:
  update on each shard -> merge across shards (collective) -> finalize once.
``MergeKind`` declares how merge lowers onto the mesh:
  PSUM / PMAX / PMIN  — elementwise; the distributed layer emits one
                        lax.psum/pmax/pmin over ICI,
  TREE                — order-insensitive but not elementwise (t-digest):
                        all_gather states, fold with merge().
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Any, Callable, Sequence

from pixie_tpu.types import DataType, SemanticType


class Executor(enum.Enum):
    """Where a scalar UDF runs (ref: planner's scalar_udfs_run_on_executor
    placement rules). DEVICE = jax-traceable over jnp arrays; HOST = numpy
    (string/JSON/metadata funcs). HOST funcs with ``dict_compatible`` are
    applied to a string column's dictionary values only, and the result is
    gathered through the codes on device."""

    DEVICE = "device"
    HOST = "host"


class MergeKind(enum.Enum):
    PSUM = "psum"
    PMAX = "pmax"
    PMIN = "pmin"
    TREE = "tree"


@dataclasses.dataclass
class ScalarUDF:
    name: str
    arg_types: tuple[DataType, ...]
    out_type: DataType
    fn: Callable[..., Any]
    executor: Executor = Executor.DEVICE
    # HOST string funcs that are pure elementwise value->value maps can run
    # on the (tiny) dictionary instead of the full column.
    dict_compatible: bool = False
    # Optional init/non-column args appended after column args (e.g. the
    # substring pattern). The reference models these as init_args (udf.h).
    num_init_args: int = 0
    # True -> fn(ctx, *cols) receives the exec FunctionContext (metadata
    # state etc.; ref: udf.h FunctionContext).
    needs_ctx: bool = False
    out_semantic: SemanticType | Callable | None = None
    doc: str = ""

    def infer_semantic(self, arg_semantics: Sequence[SemanticType]) -> SemanticType:
        if callable(self.out_semantic):
            return self.out_semantic(list(arg_semantics))
        if self.out_semantic is not None:
            return self.out_semantic
        return SemanticType.ST_NONE


@dataclasses.dataclass
class UDA:
    """A vectorized, group-batched user-defined aggregate.

    - ``init(num_groups) -> state`` pytree of [num_groups, ...] tensors
    - ``update(state, gids, *cols, mask) -> state``   (jit-compatible)
    - ``merge(a, b) -> state``                        (jit-compatible)
    - ``finalize(state) -> column`` host or device; length num_groups
    Serialize/DeSerialize (udf.h:98-100) are free: states are pytrees.
    """

    name: str
    arg_types: tuple[DataType, ...]
    out_type: DataType
    init: Callable[[int], Any]
    update: Callable[..., Any]
    merge: Callable[[Any, Any], Any]
    finalize: Callable[[Any], Any]
    merge_kind: MergeKind = MergeKind.PSUM
    out_semantic: SemanticType | Callable | None = None
    # True when finalize output must be produced on host (e.g. JSON strings).
    host_finalize: bool = False
    # False when update() ignores its value column (count): the device
    # pipeline then skips staging/evaluating that column entirely — at
    # bench scale the count arg is gigabytes of HBM and upload time.
    reads_args: bool = True
    # Optional split of ``finalize`` for the device pipeline: the numeric
    # reduction (``device_finalize``: state -> [G]/[G,K] array, traceable)
    # fuses into the compiled mesh program so the host never re-uploads
    # state; ``format_output`` (host) turns that array into the output
    # column. When set, ``finalize`` must equal
    # format_output(device_finalize(state)) for host-path parity.
    device_finalize: Callable[[Any], Any] | None = None
    format_output: Callable[[Any], Any] | None = None
    # How STRING args are presented to update():
    #   "hash" — stable uint64 content hashes of the values (dictionary-
    #            independent; safe across unions and the distributed
    #            PARTIAL/MERGE split where every agent has its own
    #            write-side dictionary). Right for sketches.
    #   "code" — codes re-encoded into the agg node's latched per-column
    #            dictionary. Right for UDAs whose state/output must remain
    #            decodable back to the string (e.g. any(STRING)).
    string_args: str = "hash"
    # True when the state itself holds codes into the latched dictionary of
    # arg 0; the partial stage then ships that dictionary in the StateBatch
    # and the merge stage translates incoming codes into its own latch.
    string_state: bool = False
    # Fused-sum lane (r4): sum-family UDAs contribute f32 limb rows to ONE
    # shared one-hot einsum per block instead of issuing their own segment
    # reduction — the one-hot generation dominates MXU segment sums, so
    # batching every sum/count (and the engine's presence counter) into a
    # single einsum is ~3x cheaper than per-UDA calls (measured r4).
    #   fused_rows(col, mask) -> list of [n] f32 rows, each value an
    #     integer in [0, 255] (masked rows must contribute 0). The bound
    #     is what makes the shared einsum exact: chunk(2^16) * 255 < 2^24
    #     keeps every f32 partial sum exactly representable. Wider values
    #     must be limb-decomposed (segment.limb_rows_i64).
    #   fused_apply(state, totals) -> state, where totals is the [L, G]
    #     float64 exact per-segment sums of this UDA's rows.
    fused_rows: Callable[..., list] | None = None
    fused_apply: Callable[[Any, Any], Any] | None = None
    # Cell lane (r5): when the arg column arrives as small-dictionary
    # codes (the pipeline's int-dictionary staging), the pipeline computes
    # ONE per-(group, code) histogram on the MXU and hands it to the UDA
    # instead of per-row values — per-CELL updates turn scatter-bound
    # sketches (count-min) from ~27ns/row into ~4 (r5 measured).
    #   cell_update(state, hist, lut) -> state, hist: [G, C] int64 row
    #   counts per cell, lut: [C] the value each code stands for.
    # Must be row-order-independent and produce exactly what update()
    # would over the expanded rows.
    cell_update: Callable[[Any, Any, Any], Any] | None = None
    # True when a FLOAT64 arg may be staged to HBM as f32 without changing
    # results beyond the UDA's own approximation (e.g. t-digest centroids
    # and log-binned histogram sketches are f32-grained anyway). Cold
    # staging is host->HBM-transfer-bound, so halving sketch-arg bytes is
    # a first-query latency lever, not a precision trade.
    stage_f32_ok: bool = False
    doc: str = ""

    @property
    def supports_partial(self) -> bool:
        """All our UDAs are partial-aggregable by construction (states are
        serializable pytrees) — the reference gates this on Serialize support
        (partial_op_mgr.h:94)."""
        return True

    def infer_semantic(self, arg_semantics: Sequence[SemanticType]) -> SemanticType:
        if callable(self.out_semantic):
            return self.out_semantic(list(arg_semantics))
        if self.out_semantic is not None:
            return self.out_semantic
        return SemanticType.ST_NONE


@dataclasses.dataclass
class UDTF:
    """User-defined table function (ref: udtf.h) — produces a table.

    ``output_relation`` declares the produced schema; ``fn(ctx, **args)``
    returns a name->values dict matching it. Used for introspection sources
    like GetAgentStatus (vizier/funcs/md_udtfs).
    """

    name: str
    arg_spec: dict[str, DataType]
    fn: Callable[..., Any]
    output_relation: Any = None  # pixie_tpu.types.Relation of produced rows
    executor: Executor = Executor.HOST
    doc: str = ""
