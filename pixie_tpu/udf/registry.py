"""UDF/UDA/UDTF registry with overload resolution.

Ref: src/carnot/udf/registry.h:101 (Registry), registry.h:44 (RegistryKey:
name + argument types, with implicit INT64->FLOAT64 promotion in lookup),
type_inference.h (semantic rules). The compiler resolves function calls
against this at analysis time; the exec engine fetches definitions by key.
"""

from __future__ import annotations

from typing import Iterable, Optional

from pixie_tpu.types import DataType
from pixie_tpu.udf.udf import UDA, UDTF, ScalarUDF


class RegistryKey:
    __slots__ = ("name", "arg_types")

    def __init__(self, name: str, arg_types: Iterable[DataType]):
        self.name = name
        self.arg_types = tuple(arg_types)

    def __hash__(self):
        return hash((self.name, self.arg_types))

    def __eq__(self, other):
        return (self.name, self.arg_types) == (other.name, other.arg_types)

    def __repr__(self):
        args = ",".join(t.name for t in self.arg_types)
        return f"{self.name}({args})"


_WIDENING = {
    DataType.BOOLEAN: (DataType.INT64, DataType.FLOAT64),
    DataType.INT64: (DataType.FLOAT64,),
    DataType.TIME64NS: (DataType.INT64, DataType.FLOAT64),
}


def _promotions(types: tuple[DataType, ...]):
    """Candidate signatures in preference order: exact first, then widening
    (BOOLEAN->INT64->FLOAT64, TIME64NS->INT64->FLOAT64), fewest promotions
    first (ref: registry lookup semantics, registry.h)."""
    import itertools

    options = [(t,) + _WIDENING.get(t, ()) for t in types]
    cands = sorted(
        itertools.product(*options),
        key=lambda cand: sum(a != b for a, b in zip(cand, types)),
    )
    for cand in cands:
        yield cand


class Registry:
    def __init__(self, name: str = "default"):
        self.name = name
        self._scalars: dict[RegistryKey, ScalarUDF] = {}
        self._udas: dict[RegistryKey, UDA] = {}
        self._udtfs: dict[str, UDTF] = {}

    # -- registration ------------------------------------------------------
    def register_scalar(self, udf: ScalarUDF) -> None:
        self._scalars[RegistryKey(udf.name, udf.arg_types)] = udf

    def register_uda(self, uda: UDA) -> None:
        self._udas[RegistryKey(uda.name, uda.arg_types)] = uda

    def register_udtf(self, udtf: UDTF) -> None:
        self._udtfs[udtf.name] = udtf

    # -- lookup ------------------------------------------------------------
    def lookup_scalar(
        self, name: str, arg_types: Iterable[DataType]
    ) -> Optional[ScalarUDF]:
        for cand in _promotions(tuple(arg_types)):
            udf = self._scalars.get(RegistryKey(name, cand))
            if udf is not None:
                return udf
        return None

    def lookup_uda(self, name: str, arg_types: Iterable[DataType]) -> Optional[UDA]:
        for cand in _promotions(tuple(arg_types)):
            uda = self._udas.get(RegistryKey(name, cand))
            if uda is not None:
                return uda
        return None

    def lookup_udtf(self, name: str) -> Optional[UDTF]:
        return self._udtfs.get(name)

    def has_scalar(self, name: str) -> bool:
        return any(k.name == name for k in self._scalars)

    def has_uda(self, name: str) -> bool:
        return any(k.name == name for k in self._udas)

    def scalar_names(self) -> set[str]:
        return {k.name for k in self._scalars}

    def scalar_overloads(self, name: str):
        """All registered overloads for a scalar name (public accessor so
        callers never reach into _scalars)."""
        return [f for k, f in self._scalars.items() if k.name == name]

    def uda_names(self) -> set[str]:
        return {k.name for k in self._udas}

    def docs(self) -> dict[str, str]:
        """Doc extraction (ref: udf/doc.h)."""
        out = {}
        for k, f in self._scalars.items():
            out[repr(k)] = f.doc
        for k, a in self._udas.items():
            out[repr(k)] = a.doc
        for n, t in self._udtfs.items():
            out[n] = t.doc
        return out


_default: Registry | None = None


def default_registry() -> Registry:
    """The fully-populated builtin registry (ref: funcs/funcs.cc
    RegisterFuncsOrDie). Lazily built to keep import light."""
    global _default
    if _default is None:
        _default = Registry("builtins")
        from pixie_tpu.udf import builtins

        builtins.register_all(_default)
    return _default
