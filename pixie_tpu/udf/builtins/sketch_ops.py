"""Sketch UDAs: quantiles (log-histogram + t-digest), HLL, count-min.

Ref: src/carnot/funcs/builtins/math_sketches.h:34-82 (QuantilesUDA, t-digest —
the only sketch the reference ships; HLL and count-min are net-new here, per
SURVEY.md §6). Output format parity: quantiles finalize to a JSON string
{"p01":..,"p10":..,"p25":..,"p50":..,"p75":..,"p90":..,"p99":..} with
ST_QUANTILES semantics so `px.pluck_float64(col, 'p50')` works unchanged.

The default `quantiles` UDA uses the log-histogram sketch (merge == add ==
one lax.psum over ICI); `quantiles_tdigest` is the t-digest variant whose
merge is a TREE contract (all-gather + sort-recompress).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from pixie_tpu.ops import countmin, histogram, hll, segment, tdigest
from pixie_tpu.types import DataType, SemanticType
from pixie_tpu.udf.registry import Registry
from pixie_tpu.udf.udf import UDA, MergeKind

F = DataType.FLOAT64
I = DataType.INT64
S = DataType.STRING

QUANTILE_KEYS = ("p01", "p10", "p25", "p50", "p75", "p90", "p99")
QUANTILE_QS = (0.01, 0.10, 0.25, 0.50, 0.75, 0.90, 0.99)


def _quantile_semantic(sems):
    if sems and sems[0] in (
        SemanticType.ST_DURATION_NS,
        SemanticType.ST_TIME_NS,
    ):
        return SemanticType.ST_DURATION_NS_QUANTILES
    return SemanticType.ST_QUANTILES


def _format_quantiles(qv: np.ndarray) -> np.ndarray:
    """[G, 7] quantile values -> JSON strings (host finalize)."""
    out = np.empty(qv.shape[0], dtype=object)
    for g in range(qv.shape[0]):
        out[g] = (
            "{"
            + ",".join(
                f'"{k}":{float(qv[g, i]):.6g}' for i, k in enumerate(QUANTILE_KEYS)
            )
            + "}"
        )
    return out


def register(r: Registry) -> None:
    def hist_quantiles_uda():
        return UDA(
            name="quantiles",
            arg_types=(F,),
            out_type=S,
            init=lambda g: histogram.init(g),
            update=lambda st, gids, col, mask=None: histogram.update(
                st, gids, col, mask
            ),
            merge=histogram.merge,
            finalize=lambda st: _format_quantiles(
                np.asarray(histogram.quantile_values(st, QUANTILE_QS))
            ),
            device_finalize=lambda st: histogram.quantile_values(
                st, QUANTILE_QS
            ),
            format_output=_format_quantiles,
            merge_kind=MergeKind.PSUM,
            out_semantic=_quantile_semantic,
            host_finalize=True,
            stage_f32_ok=True,  # log-bin assignment is way coarser than f32
            doc=(
                "Approximate p01..p99 via a log-binned histogram sketch "
                "(DDSketch-style; ~1.4% relative error; psum-mergeable)."
            ),
        )

    r.register_uda(hist_quantiles_uda())

    def tdigest_uda():
        return UDA(
            name="quantiles_tdigest",
            arg_types=(F,),
            out_type=S,
            init=lambda g: tdigest.init(g),
            update=lambda st, gids, col, mask=None: tdigest.update(
                st, gids, col, mask
            ),
            merge=tdigest.merge,
            finalize=lambda st: _format_quantiles(
                np.asarray(tdigest.quantile_values(st, QUANTILE_QS))
            ),
            device_finalize=lambda st: tdigest.quantile_values(
                st, QUANTILE_QS
            ),
            format_output=_format_quantiles,
            merge_kind=MergeKind.TREE,
            out_semantic=_quantile_semantic,
            host_finalize=True,
            stage_f32_ok=True,  # centroid means/weights are f32 already
            doc="Approximate p01..p99 via a static-shape merging t-digest.",
        )

    r.register_uda(tdigest_uda())

    def hll_uda(arg_t):
        return UDA(
            name="approx_count_distinct",
            arg_types=(arg_t,),
            out_type=I,
            init=lambda g: hll.init(g),
            update=lambda st, gids, col, mask=None: hll.update(st, gids, col, mask),
            merge=hll.merge,
            # Cell lane: int-dict-staged columns (<=256 distinct) update
            # registers from the per-(group, code) presence histogram —
            # the pipeline only routes INT64 columns here, so the LUT
            # hashes exactly like the row path's raw values.
            cell_update=hll.cell_update,
            finalize=lambda st: jnp.round(hll.estimate(st)).astype(jnp.int64),
            merge_kind=MergeKind.PMAX,
            doc=(
                "Approximate distinct count via HyperLogLog "
                "(2048 registers, ~2.3% error; pmax-mergeable). Net-new vs "
                "the reference. High-cardinality columns update registers "
                "via the r8 sort–compact lane above segment.SORTED_MIN_ROWS "
                "(O(registers) scatter instead of O(rows)); small-domain "
                "columns keep the MXU cell lane."
            ),
        )

    for t in (I, F, S):  # strings arrive as dictionary codes
        r.register_uda(hll_uda(t))

    def countmin_uda(arg_t):
        return UDA(
            name="count_min",
            arg_types=(arg_t,),
            out_type=S,
            init=lambda g: {
                "cm": countmin.init(g),
                "total": jnp.zeros((g,), jnp.int64),
            },
            update=lambda st, gids, col, mask=None: {
                "cm": countmin.update(st["cm"], gids, col, mask),
                "total": st["total"]
                + segment.seg_count(gids, st["total"].shape[0], mask),
            },
            merge=lambda a, b: {"cm": a["cm"] + b["cm"], "total": a["total"] + b["total"]},
            cell_update=lambda st, hist, lut: {
                "cm": countmin.cell_update(st["cm"], hist, lut),
                "total": st["total"] + hist.sum(axis=1),
            },
            finalize=lambda st: _format_cm(st),
            device_finalize=lambda st: jnp.stack(
                [st["total"], st["cm"].max(axis=(1, 2))], axis=1
            ),
            format_output=_format_cm_totals,
            merge_kind=MergeKind.PSUM,
            host_finalize=True,
            doc=(
                "Count-min frequency sketch (4x8192; psum-mergeable). "
                "Finalize emits sketch metadata JSON; use pixie_tpu.ops."
                "countmin.query for point lookups. Net-new vs the "
                "reference. Bucket counts ride the r8 sort–compact lane "
                "above segment.SORTED_MIN_ROWS; the cell lane serves "
                "small-domain columns."
            ),
        )

    for t in (I, S):
        r.register_uda(countmin_uda(t))


def _format_cm(st) -> np.ndarray:
    cm = np.asarray(st["cm"])
    total = np.asarray(st["total"])
    return _format_cm_totals(
        np.stack([total, cm.max(axis=(1, 2), initial=0)], axis=1)
    )


def _format_cm_totals(arr) -> np.ndarray:
    """[G, 2] (total, max_est) -> metadata JSON (depth/width are static)."""
    arr = np.asarray(arr)
    out = np.empty(arr.shape[0], dtype=object)
    for g in range(arr.shape[0]):
        out[g] = (
            f'{{"total":{int(arr[g, 0])},"depth":{countmin.DEFAULT_DEPTH},'
            f'"width":{countmin.DEFAULT_WIDTH},"max_est":{int(arr[g, 1])}}}'
        )
    return out
