"""ML UDAs/UDFs: reservoir sampling + streaming k-means.

Ref: src/carnot/funcs/builtins/ml_ops.h:88 (KMeansUDA — streaming coreset,
Lloyd's at finalize, JSON centers out), :145 (ReservoirSampleUDA), and the
KMeansUDF transform (:123). TPU re-design per pixie_tpu/ops/ml.py: fixed-
size priority reservoirs instead of pointer coresets. reservoir_sample
runs fully on device (static-shape, TREE merge); kmeans parses JSON
embeddings so it is a HOST UDA (string_args="values" keeps it off the
device matcher).
"""

from __future__ import annotations

import json

import jax
import jax.numpy as jnp
import numpy as np

from pixie_tpu.ops import hashing, ml
from pixie_tpu.types import DataType
from pixie_tpu.udf.registry import Registry
from pixie_tpu.udf.udf import UDA, Executor, MergeKind, ScalarUDF

F = DataType.FLOAT64
I = DataType.INT64
S = DataType.STRING

KMEANS_SAMPLE = 128  # per-group reservoir feeding Lloyd's
KMEANS_MAX_D = 64  # reference KMeansUDA default dimensionality


def register(r: Registry) -> None:
    def reservoir_uda(arg_t):
        dtype = jnp.int64 if arg_t == I else jnp.float64
        return UDA(
            name="reservoir_sample",
            arg_types=(arg_t,),
            out_type=S,
            init=lambda g: ml.reservoir_init(g, dtype=dtype),
            update=lambda st, gids, col, mask=None: ml.reservoir_update(
                st, gids, col, mask
            ),
            merge=ml.reservoir_merge,
            finalize=ml.reservoir_finalize,
            merge_kind=MergeKind.TREE,
            host_finalize=True,
            doc=(
                "Uniform sample of up to 64 values per group "
                "(ml_ops.h:145 ReservoirSampleUDA; priority-reservoir "
                "re-design, device-resident)."
            ),
        )

    for t in (I, F):
        r.register_uda(reservoir_uda(t))

    # -- kmeans: host UDA over JSON embedding strings -----------------------
    def km_init(g: int):
        return {
            "pts": np.zeros((g, KMEANS_SAMPLE, KMEANS_MAX_D), np.float32),
            "pri": np.full((g, KMEANS_SAMPLE), -np.inf, np.float64),
            "count": np.zeros((g,), np.int64),
            "k": np.full((g,), -1, np.int64),
            "d": np.full((g,), -1, np.int64),
        }

    def km_update(st, gids, emb_col, k_col, mask=None):
        # Host-only UDA: the AggNode rebinds its state to the return value
        # and nothing else aliases it, so in-place mutation is safe — a
        # defensive deep copy of [G, 128, 64] per (possibly 1-row) batch
        # would dominate streaming updates.
        st = {key: np.asarray(v) for key, v in st.items()}
        embs = np.atleast_1d(np.asarray(emb_col, dtype=object))
        gids = np.asarray(gids)
        ks = np.asarray(k_col)
        n = len(embs)
        # Priorities mix the embedding CONTENT with a monotonically
        # advancing stream index (count counts every row, like the
        # reference's Update which increments before parsing): either
        # alone can repeat across batches/values and bias the sample.
        from pixie_tpu.table.column import _fnv1a64

        salt = int(st["count"].sum())
        idx_h = np.asarray(
            hashing.hash64(jnp.arange(salt, salt + n, dtype=jnp.int64))
        ).astype(np.uint64)
        pri = np.empty(n, np.float64)
        for i in range(n):
            pri[i] = float(
                (_fnv1a64(str(embs[i])) ^ idx_h[i]) >> np.uint64(11)
            ) / float(1 << 53)
        for i in range(n):
            if mask is not None and not mask[i]:
                continue
            g = int(gids[i])
            st["count"][g] += 1
            try:
                vec = np.asarray(json.loads(embs[i]), np.float32)
            except (ValueError, TypeError):
                continue
            d = min(len(vec), KMEANS_MAX_D)
            if st["k"][g] == -1:
                st["k"][g] = int(ks[i]) if np.ndim(ks) else int(ks)
                st["d"][g] = d
            slot = int(np.argmin(st["pri"][g]))
            if pri[i] > st["pri"][g][slot]:
                st["pri"][g][slot] = pri[i]
                st["pts"][g][slot] = 0.0
                st["pts"][g][slot, :d] = vec[:d]
        return st

    def km_merge(a, b):
        a = {key: np.asarray(v) for key, v in a.items()}
        b = {key: np.asarray(v) for key, v in b.items()}
        pts, pri = ml.topk_by_priority(
            a["pts"], b["pts"], a["pri"], b["pri"], KMEANS_SAMPLE
        )
        return {
            "pts": np.asarray(pts),
            "pri": np.asarray(pri),
            "count": a["count"] + b["count"],
            "k": np.where(a["k"] >= 0, a["k"], b["k"]),
            "d": np.where(a["d"] >= 0, a["d"], b["d"]),
        }

    def km_finalize(st) -> np.ndarray:
        pts = np.asarray(st["pts"])
        pri = np.asarray(st["pri"])
        karr = np.asarray(st["k"])
        darr = np.asarray(st["d"])
        out = np.full(pts.shape[0], '{"k":0,"centers":[]}', dtype=object)
        # One vmapped Lloyd run per distinct k (k is static in the jit):
        # groups batch together instead of one compile + dispatch each.
        w = np.isfinite(pri).astype(np.float32)
        fit = jax.jit(
            jax.vmap(ml.kmeans_fit, in_axes=(0, 0, None)),
            static_argnums=2,
        )
        for k in np.unique(karr[karr > 0]):
            sel = np.nonzero(karr == k)[0]
            centers = np.asarray(
                fit(jnp.asarray(pts[sel]), jnp.asarray(w[sel]), int(k))
            )
            for j, g in enumerate(sel):
                d = int(darr[g])
                out[g] = json.dumps(
                    {
                        "k": int(k),
                        "centers": [
                            [round(float(x), 6) for x in c]
                            for c in centers[j][:, :d]
                        ],
                    }
                )
        return out

    r.register_uda(
        UDA(
            name="kmeans",
            arg_types=(S, I),
            out_type=S,
            init=km_init,
            update=km_update,
            merge=km_merge,
            finalize=km_finalize,
            merge_kind=MergeKind.TREE,
            host_finalize=True,
            string_args="values",
            doc=(
                "Streaming k-means over JSON float-array embeddings "
                "(ml_ops.h:88 KMeansUDA): reservoir-sampled points, "
                "Lloyd's at finalize, JSON centers out."
            ),
        )
    )

    # -- kmeans transform (ml_ops.h:123 KMeansUDF) -------------------------
    def kmeans_predict(emb, model_json):
        embs = np.atleast_1d(np.asarray(emb, dtype=object))
        models = np.atleast_1d(np.asarray(model_json, dtype=object))
        out = np.empty(len(embs), np.int64)
        cache: dict = {}
        for i in range(len(embs)):
            m = models[i] if len(models) > 1 else models[0]
            if m not in cache:
                try:
                    cache[m] = np.asarray(
                        json.loads(m)["centers"], np.float32
                    )
                except (ValueError, TypeError, KeyError):
                    cache[m] = None  # malformed model: same -1 sentinel
            centers = cache[m]
            if centers is None or centers.size == 0:
                out[i] = -1
                continue
            try:
                vec = np.asarray(json.loads(embs[i]), np.float32)
            except (ValueError, TypeError):
                out[i] = -1
                continue
            d = min(vec.shape[0], centers.shape[1])
            if d == 0:  # '[]' parses but carries no information
                out[i] = -1
                continue
            out[i] = ml.kmeans_assign(vec[:d], centers[:, :d])
        return out

    r.register_scalar(
        ScalarUDF(
            "kmeans_predict",
            (S, S),
            I,
            kmeans_predict,
            Executor.HOST,
            dict_compatible=False,
            doc="Nearest kmeans-center index for a JSON embedding "
            "(ml_ops.h KMeansUDF::Transform).",
        )
    )
    def _transformer(docs):
        import numpy as np

        from pixie_tpu.ops.transformer import default_pool

        arr = np.atleast_1d(np.asarray(docs, dtype=object))
        out = np.empty(len(arr), dtype=object)
        with default_pool().get() as ex:
            for i, d in enumerate(arr):
                out[i] = ex.execute(str(d))
        return out

    r.register_scalar(
        ScalarUDF(
            "transformer",
            (S,),
            S,
            _transformer,
            Executor.HOST,
            dict_compatible=True,
            doc="Sentence embedding from JSON token ids via the pooled "
            "JAX transformer executor (ml_ops.h TransformerUDF + "
            "exec/ml/transformer_executor.h re-implemented TPU-native; "
            "model_pool.h borrow-pool semantics).",
        )
    )

    def _sentencepiece(texts):
        import numpy as np

        from pixie_tpu.ops.transformer import tokenize

        arr = np.atleast_1d(np.asarray(texts, dtype=object))
        out = np.empty(len(arr), dtype=object)
        for i, t in enumerate(arr):
            out[i] = tokenize(str(t))
        return out

    r.register_scalar(
        ScalarUDF(
            "sentencepiece",
            (S,),
            S,
            _sentencepiece,
            Executor.HOST,
            dict_compatible=True,
            doc="string -> JSON token ids (ml_ops.h SentencePieceUDF "
            "contract; hash-bucketed subwords stand in for the "
            "/sentencepiece.proto asset that does not ship in-tree).",
        )
    )

