"""Collection UDAs (ref: src/carnot/funcs/builtins/collections.h — AnyUDA
:33). ``any`` keeps an arbitrary observed value per group; on TPU that is a
segment-max over values (codes for strings), which is deterministic and
collective-mergeable (pmax)."""

from __future__ import annotations

import jax.numpy as jnp

from pixie_tpu.ops import segment
from pixie_tpu.types import DataType
from pixie_tpu.udf.registry import Registry
from pixie_tpu.udf.udf import UDA, MergeKind

F = DataType.FLOAT64
I = DataType.INT64
S = DataType.STRING
B = DataType.BOOLEAN
T = DataType.TIME64NS


def register(r: Registry) -> None:
    def any_uda(arg_t):
        # Codes/ints: track max, init at int64 min (or -inf for floats).
        if arg_t == F:
            dtype, ident = jnp.float64, -jnp.inf
        else:
            dtype, ident = jnp.int64, jnp.iinfo(jnp.int64).min

        def fin(st):
            zero = jnp.zeros_like(st)
            return jnp.where(st == ident, zero, st)

        if arg_t in (S, B):
            # Dictionary codes / bools fit int32, and TPU s64 scatter-max
            # is ~12x the cost of s32 (r4 measurement) — reduce each block
            # in int32, widen once per block, and remap the int32 identity
            # (all-masked segments) back to the int64 identity.
            i32_min = jnp.iinfo(jnp.int32).min

            def update(st, gids, col, mask=None):
                m32 = segment.seg_max(
                    col.astype(jnp.int32), gids, st.shape[0], mask
                )
                m64 = jnp.where(m32 == i32_min, ident, m32.astype(dtype))
                return jnp.maximum(st, m64)

        else:

            def update(st, gids, col, mask=None):
                return jnp.maximum(
                    st,
                    segment.seg_max(
                        col.astype(dtype), gids, st.shape[0], mask
                    ),
                )

        return UDA(
            name="any",
            arg_types=(arg_t,),
            out_type=arg_t,
            init=lambda g: jnp.full((g,), ident, dtype),
            update=update,
            merge=jnp.maximum,
            finalize=fin,
            merge_kind=MergeKind.PMAX,
            out_semantic=lambda sems: sems[0] if sems else None,
            # String state holds codes that must decode back to the value,
            # so it rides the latched-dictionary path, not content hashes.
            string_args="code",
            string_state=(arg_t == S),
            doc="An arbitrary (deterministic: max) value from the group.",
        )

    for t in (F, I, S, B, T):
        r.register_uda(any_uda(t))
