"""Conditional scalar UDFs (ref: src/carnot/funcs/builtins/conditionals.h —
SelectUDF). Numeric select is a device jnp.where; string select operates on
codes only when both branches share a dictionary, so it is registered HOST
and the expression evaluator re-encodes as needed."""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from pixie_tpu.types import DataType
from pixie_tpu.udf.registry import Registry
from pixie_tpu.udf.udf import Executor, ScalarUDF

S = DataType.STRING
I = DataType.INT64
B = DataType.BOOLEAN
F = DataType.FLOAT64
T = DataType.TIME64NS


def register(r: Registry) -> None:
    for t in (F, I, B, T):
        r.register_scalar(
            ScalarUDF(
                "select",
                (B, t, t),
                t,
                lambda c, a, b: jnp.where(c, a, b),
                Executor.DEVICE,
                out_semantic=lambda sems: sems[1] if len(sems) > 1 else None,
            )
        )

    def select_str(cond, a, b):
        cond = np.asarray(cond, dtype=bool)
        n = len(cond)
        pick = lambda col, i: col[i] if isinstance(col, np.ndarray) else col
        out = np.empty(n, dtype=object)
        for i in range(n):
            out[i] = pick(a, i) if cond[i] else pick(b, i)
        return out

    r.register_scalar(
        ScalarUDF("select", (B, S, S), S, select_str, Executor.HOST)
    )
