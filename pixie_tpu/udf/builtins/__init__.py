"""Builtin function library (ref: src/carnot/funcs/ — RegisterFuncsOrDie in
funcs/funcs.cc). Each module registers its functions into a Registry."""

from pixie_tpu.udf.registry import Registry


def register_all(registry: Registry) -> None:
    from pixie_tpu.udf.builtins import (
        collections,
        conditionals,
        json_ops,
        math_ops,
        md_udtfs,
        metadata_ops,
        ml_ops,
        security_ops,
        sketch_ops,
        string_ops,
        time_ops,
    )

    math_ops.register(registry)
    sketch_ops.register(registry)
    string_ops.register(registry)
    json_ops.register(registry)
    conditionals.register(registry)
    time_ops.register(registry)
    collections.register(registry)
    metadata_ops.register(registry)
    md_udtfs.register(registry)
    ml_ops.register(registry)
    security_ops.register(registry)
