"""PII redaction, SQL normalization, URI, request-path clustering, CIDR.

Ref: src/carnot/funcs/builtins/pii_ops.{h,cc} (redact_pii_best_effort —
'<REDACTED_$TYPE>' substitution for IPs, emails, MACs, CC numbers, IMEI,
SSNs), sql_ops.{h,cc} (normalize_mysql / normalize_pgsql — literals out,
params captured, JSON result), uri_ops.h (uri_parse / uri_recompose),
request_path_ops.{h,cc}:230 (_build_request_path_clusters /
_predict_request_path_cluster / _match_endpoint), net/net_ops.cc
(cidrs_contain_ip). All host UDFs: string content work stays off the
device (scalar_udfs_run_on_executor precedent)."""

from __future__ import annotations

import ipaddress
import json
import re

import numpy as np

from pixie_tpu.types import DataType
from pixie_tpu.udf.registry import Registry
from pixie_tpu.udf.udf import UDA, Executor, MergeKind, ScalarUDF

S = DataType.STRING
I = DataType.INT64
B = DataType.BOOLEAN

# Order matters: longer/stricter patterns first so e.g. IPv4 inside an
# IPv6-mapped literal or an email's host part redacts coherently.
_PII_PATTERNS = [
    ("EMAIL", re.compile(r"[A-Za-z0-9._%+-]+@[A-Za-z0-9.-]+\.[A-Za-z]{2,}")),
    (
        # Before IPV6: six colon-separated 2-hex groups parse as both.
        "MAC_ADDR",
        re.compile(r"\b(?:[0-9A-Fa-f]{2}[:-]){5}[0-9A-Fa-f]{2}\b"),
    ),
    (
        # Full 8-group form or a compressed '::' form only — a looser
        # colon-hex run would wipe hh:mm:ss timestamps in log text.
        # Uppercase tags match the reference's emitted format
        # (pii_ops.cc:123,139 '<REDACTED_IPV4>'/'<REDACTED_IPV6>'; ADVICE r3).
        "IPV6",
        re.compile(
            r"\b(?:(?:[0-9A-Fa-f]{1,4}:){7}[0-9A-Fa-f]{1,4}"
            r"|(?:[0-9A-Fa-f]{1,4}:)+:(?:[0-9A-Fa-f]{1,4}(?::[0-9A-Fa-f]{1,4})*)?)\b"
        ),
    ),
    (
        "IPV4",
        re.compile(r"\b(?:\d{1,3}\.){3}\d{1,3}\b"),
    ),
    # IMEI before CC_NUMBER: a dashed IMEI is 15 digits and would
    # otherwise always be swallowed by the credit-card pattern.
    ("IMEI", re.compile(r"\b\d{2}-\d{6}-\d{6}-\d\b")),
    (
        # Before CC_NUMBER, whose digit-run pattern would eat an IBAN's
        # tail (reference parity: pii_ops.cc IBAN rule). Country code +
        # 2 check digits + 11-30 BBAN chars, optionally space-grouped;
        # candidates must then pass the ISO 13616 mod-97 check (see
        # _valid_iban) so uppercase build ids don't get redacted.
        "IBAN",
        re.compile(r"\b[A-Z]{2}\d{2}(?: ?[A-Z0-9]{4}){2,7}(?: ?[A-Z0-9]{1,4})?\b"),
    ),
    (
        "CC_NUMBER",
        re.compile(r"\b(?:\d[ -]?){13,19}\b"),
    ),
    ("SSN", re.compile(r"\b\d{3}-\d{2}-\d{4}\b")),
]


def _valid_iban(candidate: str) -> bool:
    """ISO 13616 validation: length 15-34 and mod-97 == 1 (letters map to
    10..35 after rotating the first four chars to the end)."""
    s = candidate.replace(" ", "")
    if not 15 <= len(s) <= 34:
        return False
    rotated = s[4:] + s[:4]
    digits = "".join(
        str(ord(ch) - 55) if ch.isalpha() else ch for ch in rotated
    )
    return int(digits) % 97 == 1


def _redact_one(s: str) -> str:
    for tag, pat in _PII_PATTERNS:
        if tag == "IBAN":
            s = pat.sub(
                lambda m: (
                    "<REDACTED_IBAN>" if _valid_iban(m.group(0)) else m.group(0)
                ),
                s,
            )
        else:
            s = pat.sub(f"<REDACTED_{tag}>", s)
    return s


# SQL literal patterns shared by both dialects; ONE left-to-right pass so
# params stay in query order (two passes would list all strings before any
# number regardless of position).
_SQL_LITERAL = re.compile(
    r"'(?:[^'\\]|\\.|'')*'"
    r"|(?<![\w$])[-+]?\d+(?:\.\d+)?(?:[eE][-+]?\d+)?\b"
)


def _normalize_sql(query: str, placeholder) -> str:
    """Replace literals with placeholders; JSON result mirrors the
    reference's {query, params, error} shape."""
    params: list[str] = []

    def repl(m):
        params.append(m.group(0))
        return placeholder(len(params))

    try:
        out = _SQL_LITERAL.sub(repl, query)
        return json.dumps({"query": out, "params": params, "error": ""})
    except Exception as e:  # pragma: no cover - defensive
        return json.dumps({"query": "", "params": [], "error": str(e)})


_PATH_ID_SEGMENT = re.compile(
    r"^(?:\d+|0[xX][0-9a-fA-F]+|[0-9a-fA-F]{8,}|"
    r"[0-9a-fA-F]{8}-[0-9a-fA-F]{4}-[0-9a-fA-F]{4}-[0-9a-fA-F]{4}-"
    r"[0-9a-fA-F]{12})$"
)


def _path_template(path: str) -> str:
    """Template a request path: id-like segments (numbers, hex, uuids)
    become '*' (the reference clusters paths by similarity; id-segment
    generalization is the shape its clusters converge to)."""
    base = path.split("?", 1)[0]
    segs = base.split("/")
    out = [
        "*" if _PATH_ID_SEGMENT.match(seg) else seg for seg in segs
    ]
    return "/".join(out)


def _lift(fn, out_dtype=object):
    def wrapper(*cols):
        arrs = [np.atleast_1d(np.asarray(c, dtype=object)) for c in cols]
        n = max(len(a) for a in arrs)
        out = np.empty(n, dtype=out_dtype)
        for i in range(n):
            out[i] = fn(*(a[i] if len(a) > 1 else a[0] for a in arrs))
        return out

    return wrapper


def register(r: Registry) -> None:
    def reg(name, args, out, fn, out_dtype=object, doc=""):
        r.register_scalar(
            ScalarUDF(
                name, args, out, _lift(fn, out_dtype), Executor.HOST,
                dict_compatible=True, doc=doc,
            )
        )

    reg(
        "redact_pii_best_effort", (S,), S, _redact_one,
        doc="Best-effort PII redaction: '<REDACTED_$TYPE>' for emails, "
        "IPs, MACs, CC numbers, IMEI, SSNs (pii_ops.h RedactPIIUDF).",
    )
    reg(
        "normalize_mysql", (S,), S,
        lambda q: _normalize_sql(q, lambda i: "?"),
        doc="MySQL query normalization: literals -> '?', params captured "
        "(sql_ops.h NormalizeMySQLUDF).",
    )
    reg(
        "normalize_pgsql", (S,), S,
        lambda q: _normalize_sql(q, lambda i: f"${i}"),
        doc="PostgreSQL query normalization: literals -> $N "
        "(sql_ops.h NormalizePostgresSQLUDF).",
    )
    # 2-arg forms matching the reference signatures exactly (sql_ops.h:
    # pgsql takes the command TAG string, mysql the command CODE int);
    # px/sql_queries calls these over the events tables.
    reg(
        "normalize_pgsql", (S, S), S,
        lambda q, _cmd: _normalize_sql(q, lambda i: f"${i}"),
        doc="PostgreSQL query normalization with command tag "
        "(sql_ops.h NormalizePostgresSQLUDF).",
    )
    reg(
        "normalize_mysql", (S, I), S,
        lambda q, _cmd: _normalize_sql(q, lambda i: "?"),
        doc="MySQL query normalization with command code "
        "(sql_ops.h NormalizeMySQLUDF).",
    )

    def uri_parse(uri: str) -> str:
        from urllib.parse import urlsplit

        try:
            p = urlsplit(uri)
        except ValueError:
            return "Failed to parse URI"
        out = {}
        if p.scheme:
            out["scheme"] = p.scheme
        if p.username:
            out["userInfo"] = p.username + (
                f":{p.password}" if p.password else ""
            )
        if p.hostname:
            out["host"] = p.hostname
        try:
            if p.port is not None:
                out["port"] = str(p.port)
        except ValueError:
            pass
        if p.path:
            out["path"] = p.path
        if p.query:
            out["query"] = p.query
        if p.fragment:
            out["fragment"] = p.fragment
        return json.dumps(out)

    reg("uri_parse", (S,), S, uri_parse,
        doc="URI -> JSON {scheme,userInfo,host,port,path,query,fragment} "
        "(uri_ops.h URIParseUDF).")

    def uri_recompose(scheme, user_info, host, port, path, query, fragment):
        try:
            port = int(port)
        except (TypeError, ValueError):
            return "Failed to recompose URI"
        if port < 0:
            return "Failed to recompose URI"
        out = ""
        if scheme:
            out += f"{scheme}://"
        if user_info:
            out += f"{user_info}@"
        out += str(host)
        if port:
            out += f":{port}"
        out += str(path)
        if query:
            out += f"?{query}"
        if fragment:
            out += f"#{fragment}"
        return out

    r.register_scalar(
        ScalarUDF(
            "uri_recompose", (S, S, S, I, S, S, S), S,
            _lift(uri_recompose), Executor.HOST, dict_compatible=False,
            doc="Recompose a URI from parts (uri_ops.h URIRecomposeUDF).",
        )
    )

    def cidrs_contain_ip(cidrs_json: str, ip: str) -> bool:
        try:
            cidrs = json.loads(cidrs_json)
            addr = ipaddress.ip_address(ip)
        except ValueError:
            return False
        for c in cidrs if isinstance(cidrs, list) else [cidrs]:
            try:
                if addr in ipaddress.ip_network(c, strict=False):
                    return True
            except ValueError:
                continue
        return False

    reg("cidrs_contain_ip", (S, S), B, cidrs_contain_ip, out_dtype=bool,
        doc="True if the IP is inside any CIDR of the JSON list "
        "(net/net_ops.cc CIDRsContainIPUDF).")

    reg(
        "_predict_request_path_cluster", (S,), S, _path_template,
        doc="Cluster template for a request path: id-like segments -> '*' "
        "(request_path_ops.h RequestPathClusteringPredictUDF).",
    )

    def match_endpoint(path: str, template: str) -> bool:
        return _path_template(path) == template or path == template

    reg("_match_endpoint", (S, S), B, match_endpoint, out_dtype=bool,
        doc="Does the path belong to the endpoint template? "
        "(request_path_ops.h RequestPathEndpointMatcherUDF).")

    # -- clustering UDA (request_path_ops.h:230) ---------------------------
    def rpc_init(g: int):
        return {"templates": np.full((g,), "[]", dtype=object)}

    def rpc_update(st, gids, paths, mask=None):
        st = {"templates": np.asarray(st["templates"], dtype=object).copy()}
        paths = np.atleast_1d(np.asarray(paths, dtype=object))
        gids = np.asarray(gids)
        per_group: dict[int, set] = {}
        for i in range(len(paths)):
            if mask is not None and not mask[i]:
                continue
            per_group.setdefault(int(gids[i]), set()).add(
                _path_template(str(paths[i]))
            )
        for g, fresh in per_group.items():
            cur = set(json.loads(st["templates"][g]))
            st["templates"][g] = json.dumps(sorted(cur | fresh))
        return st

    def rpc_merge(a, b):
        ta = np.asarray(a["templates"], dtype=object)
        tb = np.asarray(b["templates"], dtype=object)
        out = np.empty(len(ta), dtype=object)
        for g in range(len(ta)):
            out[g] = json.dumps(
                sorted(set(json.loads(ta[g])) | set(json.loads(tb[g])))
            )
        return {"templates": out}

    r.register_uda(
        UDA(
            name="_build_request_path_clusters",
            arg_types=(S,),
            out_type=S,
            init=rpc_init,
            update=rpc_update,
            merge=rpc_merge,
            finalize=lambda st: np.asarray(st["templates"], dtype=object),
            merge_kind=MergeKind.TREE,
            host_finalize=True,
            string_args="values",
            doc="Endpoint templates observed per group, as a JSON list "
            "(request_path_ops.h RequestPathClusteringFitUDA; id-segment "
            "generalization instead of the reference's online "
            "similarity clustering).",
        )
    )
