"""String scalar UDFs (host executor, dictionary-compatible where elementwise).

Ref: src/carnot/funcs/builtins/string_ops.h. These run on CPU by design (the
reference's planner likewise pins string UDFs to executors via
scalar_udfs_run_on_executor rules) — but because our string columns are
dictionary-encoded, any elementwise string->X function marked
``dict_compatible`` is evaluated once per *distinct* value on the host and
broadcast through the codes on device, so the per-row cost is a gather.
"""

from __future__ import annotations

import re

import numpy as np

from pixie_tpu.types import DataType
from pixie_tpu.udf.registry import Registry
from pixie_tpu.udf.udf import Executor, ScalarUDF

S = DataType.STRING
I = DataType.INT64
B = DataType.BOOLEAN
F = DataType.FLOAT64


def _vec(fn, out_dtype=object):
    """Lift an elementwise python fn over numpy object arrays (broadcasting
    scalar args)."""

    def wrapper(*cols):
        n = max((len(c) for c in cols if isinstance(c, np.ndarray)), default=1)
        out = np.empty(n, dtype=out_dtype)
        for i in range(n):
            args = [c[i] if isinstance(c, np.ndarray) else c for c in cols]
            out[i] = fn(*args)
        return out

    return wrapper


def register(r: Registry) -> None:
    def reg(name, args, out, fn, out_dtype=object, dict_compatible=True):
        r.register_scalar(
            ScalarUDF(
                name,
                args,
                out,
                _vec(fn, out_dtype),
                Executor.HOST,
                dict_compatible=dict_compatible,
            )
        )

    reg("contains", (S, S), B, lambda s, sub: sub in s, np.bool_)
    reg("length", (S,), I, len, np.int64)
    reg("find", (S, S), I, lambda s, sub: s.find(sub), np.int64)
    reg(
        "substring",
        (S, I, I),
        S,
        lambda s, start, length: s[int(start): int(start) + int(length)],
    )
    reg("toLower", (S,), S, str.lower)
    reg("toUpper", (S,), S, str.upper)
    reg("trim", (S,), S, str.strip)
    reg("strip", (S,), S, str.strip)
    # string concat: plus on strings (PxL `df.a + df.b`)
    reg("add", (S, S), S, lambda a, b: a + b, dict_compatible=False)
    reg(
        "replace",
        (S, S, S),
        S,
        lambda s, old, new: s.replace(old, new),
    )
    reg("startsWith", (S, S), B, lambda s, p: s.startswith(p), np.bool_)
    reg("endsWith", (S, S), B, lambda s, p: s.endswith(p), np.bool_)

    # regex_match(regex, input) (ref: string_ops.h RegexMatchUDF arg order)
    def regex_match(regex, s):
        try:
            return re.fullmatch(regex, s) is not None
        except re.error:
            return False

    reg("regex_match", (S, S), B, regex_match, np.bool_)
    reg(
        "regex_replace",
        (S, S, S),
        S,
        lambda pattern, s, sub: re.sub(pattern, sub, s),
    )

    # itoa / atoi style conversions
    reg("string", (I,), S, lambda v: str(int(v)))
    reg("string", (F,), S, lambda v: repr(float(v)))
    reg("string", (B,), S, lambda v: "true" if v else "false")
    reg("string", (S,), S, lambda v: v)

    def _atoi(s):
        try:
            return int(s)
        except (ValueError, TypeError):
            return 0

    def _atof(s):
        try:
            return float(s)
        except (ValueError, TypeError):
            return float("nan")

    reg("atoi", (S,), I, _atoi, np.int64)
    reg("atof", (S,), F, _atof, np.float64)

    # script_reference(label, script, k1, v1, k2, v2, ...): flattened by the
    # compiler from the PxL dict literal; emits the UI deeplink JSON the
    # reference produces (ST_SCRIPT_REFERENCE).
    def script_reference(label, script, *kvs):
        import json

        args = {kvs[i]: kvs[i + 1] for i in range(0, len(kvs), 2)}
        return json.dumps(
            {"label": label, "script": script, "args": args}, sort_keys=True
        )

    for n_args in range(0, 5):
        arity = (S, S) + (S,) * (2 * n_args)
        r.register_scalar(
            ScalarUDF(
                "script_reference",
                arity,
                S,
                _vec(script_reference),
                Executor.HOST,
                dict_compatible=False,
            )
        )
