"""Metadata scalar UDFs: k8s entity lookups against the MetadataState.

Ref: src/carnot/funcs/metadata/metadata_ops.* (UPIDToServiceNameUDF et al.,
resolved against AgentMetadataState via FunctionContext). All host-executed
and dict_compatible: UPIDs/IPs are dictionary-encoded strings, so each
distinct process/endpoint resolves once per query, not once per row.
"""

from __future__ import annotations

import numpy as np

from pixie_tpu.types import DataType, SemanticType
from pixie_tpu.udf.registry import Registry
from pixie_tpu.udf.udf import Executor, ScalarUDF

S = DataType.STRING
I = DataType.INT64


def _lift(fn, out_dtype=object):
    def wrapper(ctx, *cols):
        state = ctx.metadata_state
        n = max((len(c) for c in cols if isinstance(c, np.ndarray)), default=1)
        out = np.empty(n, dtype=out_dtype)
        for i in range(n):
            args = [c[i] if isinstance(c, np.ndarray) else c for c in cols]
            out[i] = fn(state, *args)
        return out

    return wrapper


def register(r: Registry) -> None:
    def reg(name, args, out, fn, out_dtype=object, semantic=None):
        r.register_scalar(
            ScalarUDF(
                name,
                args,
                out,
                _lift(fn, out_dtype),
                Executor.HOST,
                dict_compatible=True,
                needs_ctx=True,
                out_semantic=semantic,
            )
        )

    # -- UPID resolvers ----------------------------------------------------
    def pod_of(st, upid):
        return st.pod_for_upid(upid)

    reg(
        "upid_to_pod_id",
        (S,),
        S,
        lambda st, u: (pod_of(st, u).pod_id if pod_of(st, u) else ""),
    )
    reg(
        "upid_to_pod_name",
        (S,),
        S,
        lambda st, u: (pod_of(st, u).name if pod_of(st, u) else ""),
        semantic=SemanticType.ST_POD_NAME,
    )
    reg(
        "upid_to_namespace",
        (S,),
        S,
        lambda st, u: (pod_of(st, u).namespace if pod_of(st, u) else ""),
        semantic=SemanticType.ST_NAMESPACE_NAME,
    )
    reg(
        "upid_to_node_name",
        (S,),
        S,
        lambda st, u: (pod_of(st, u).node_name if pod_of(st, u) else ""),
        semantic=SemanticType.ST_NODE_NAME,
    )

    def svc_of(st, upid):
        return st.service_for_upid(upid)

    reg(
        "upid_to_service_name",
        (S,),
        S,
        lambda st, u: (svc_of(st, u).name if svc_of(st, u) else ""),
        semantic=SemanticType.ST_SERVICE_NAME,
    )
    reg(
        "upid_to_service_id",
        (S,),
        S,
        lambda st, u: (svc_of(st, u).service_id if svc_of(st, u) else ""),
    )

    def upid_to_pid(st, u):
        try:
            return int(u.split(":")[1])
        except (IndexError, ValueError):
            return -1

    reg("upid_to_pid", (S,), I, upid_to_pid, np.int64)

    def upid_to_asid(st, u):
        try:
            return int(u.split(":")[0])
        except (IndexError, ValueError):
            return -1

    reg("upid_to_asid", (S,), I, upid_to_asid, np.int64)

    # -- pod/service id resolvers -----------------------------------------
    reg(
        "pod_id_to_pod_name",
        (S,),
        S,
        lambda st, pid: st.pods[pid].name if pid in st.pods else "",
        semantic=SemanticType.ST_POD_NAME,
    )
    reg(
        "pod_id_to_service_name",
        (S,),
        S,
        lambda st, pid: (
            st.services[st.pods[pid].service_id].name
            if pid in st.pods and st.pods[pid].service_id in st.services
            else ""
        ),
        semantic=SemanticType.ST_SERVICE_NAME,
    )
    reg(
        "pod_id_to_service_id",
        (S,),
        S,
        lambda st, pid: st.pods[pid].service_id if pid in st.pods else "",
    )
    reg(
        "pod_id_to_namespace",
        (S,),
        S,
        lambda st, pid: st.pods[pid].namespace if pid in st.pods else "",
        semantic=SemanticType.ST_NAMESPACE_NAME,
    )
    reg(
        "service_id_to_service_name",
        (S,),
        S,
        lambda st, sid: st.services[sid].name if sid in st.services else "",
        semantic=SemanticType.ST_SERVICE_NAME,
    )
    reg(
        "ip_to_pod_id",
        (S,),
        S,
        lambda st, ip: st.pod_for_ip(ip).pod_id if st.pod_for_ip(ip) else "",
    )

    def _ip_to_service_id(st, ip):
        pod = st.pod_for_ip(ip)
        return pod.service_id if pod is not None else ""

    reg("ip_to_service_id", (S,), S, _ip_to_service_id)

    def _pod_id_to_node_name(st, pid):
        pod = st.pods.get(pid)
        return pod.node_name if pod is not None else ""

    reg(
        "pod_id_to_node_name",
        (S,),
        S,
        _pod_id_to_node_name,
        semantic=SemanticType.ST_NODE_NAME,
    )
    reg(
        "nslookup",
        (S,),
        S,
        lambda st, ip: st.dns.get(ip, ip),
    )
    reg("_exec_hostname", (), S, lambda st: st.hostname)

    def _num_cpus(st):
        import os

        return os.cpu_count() or 1

    r.register_scalar(
        ScalarUDF(
            "_exec_host_num_cpus",
            (),
            I,
            _lift(lambda st: _num_cpus(st), np.int64),
            Executor.HOST,
            dict_compatible=False,
            needs_ctx=True,
        )
    )
    reg(
        "upid_to_container_name",
        (S,),
        S,
        lambda st, u: st.upid_to_container.get(u, ""),
    )
    reg(
        "upid_to_container_id",
        (S,),
        S,
        # Container ids are container names prefixed per-pod in the
        # synthetic state (no containerd runtime here); resolves to ""
        # when unknown, like the reference on missing metadata.
        lambda st, u: st.upid_to_container.get(u, ""),
    )
    reg(
        "upid_to_cmdline",
        (S,),
        S,
        lambda st, u: st.upid_to_cmdline.get(u, ""),
    )

    def _has_name(st, col_val, want):
        # Ref: HasServiceNameUDF (metadata_ops.h:3096): equality OR
        # membership when the column holds a JSON array of names (pods
        # backing several services).
        if col_val == want:
            return True
        if col_val.startswith("["):
            try:
                import json

                return want in json.loads(col_val)
            except ValueError:
                return False
        return False

    reg("has_service_name", (S, S), DataType.BOOLEAN, _has_name, np.bool_)
    reg("has_service_id", (S, S), DataType.BOOLEAN, _has_name, np.bool_)
    reg(
        "container_id_to_status",
        (S,),
        S,
        # Ref: ContainerIDToStatusUDF (metadata_ops.h:2859) — JSON status
        # blob; without a container runtime the state/reason mirror the
        # pod-status shape for known containers.
        lambda st, cid: (
            '{"state":"Running","message":"","reason":""}'
            if cid
            else '{"state":"Unknown","message":"","reason":""}'
        ),
    )
    reg("pod_name_to_pod_id", (S,), S,
        lambda st, name: next(
            (p.pod_id for p in st.pods.values() if p.name == name), ""
        ))

    def _pod_by_name(st, name):
        return next((p for p in st.pods.values() if p.name == name), None)

    reg(
        "pod_name_to_start_time",
        (S,),
        DataType.TIME64NS,
        lambda st, name: (
            _pod_by_name(st, name).start_time_ns
            if _pod_by_name(st, name)
            else 0
        ),
    )
    reg(
        "pod_name_to_status",
        (S,),
        S,
        lambda st, name: (
            '{"phase":"%s","message":"","reason":"","ready":true}'
            % _pod_by_name(st, name).phase
            if _pod_by_name(st, name)
            else '{"phase":"Unknown","message":"","reason":"","ready":false}'
        ),
    )
    reg(
        "pod_name_to_pod_ip",
        (S,),
        S,
        lambda st, name: (
            _pod_by_name(st, name).ip if _pod_by_name(st, name) else ""
        ),
        semantic=SemanticType.ST_IP_ADDRESS,
    )
    reg("service_name_to_service_id", (S,), S,
        lambda st, name: next(
            (s.service_id for s in st.services.values() if s.name == name), ""
        ))
