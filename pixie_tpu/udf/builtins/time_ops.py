"""Time scalar UDFs (ref: src/carnot/funcs/builtins/math_ops.h BinUDF and
funcs/builtins/time_ops). px.now / px.minutes etc. are compile-time values
provided by the PxL object layer (pixie_tpu.compiler.objects), not UDFs —
matching the reference where they are compiler intrinsics."""

from __future__ import annotations

import jax.numpy as jnp

from pixie_tpu.types import DataType, SemanticType
from pixie_tpu.udf.registry import Registry
from pixie_tpu.udf.udf import Executor, ScalarUDF

I = DataType.INT64
T = DataType.TIME64NS
F = DataType.FLOAT64


def register(r: Registry) -> None:
    def bin_fn(t, size):
        return t - t % jnp.maximum(size, 1)

    for args, out in [((T, I), T), ((I, I), I), ((F, I), F)]:
        r.register_scalar(
            ScalarUDF(
                "bin",
                args,
                out,
                bin_fn,
                Executor.DEVICE,
                out_semantic=lambda sems: sems[0] if sems else None,
            )
        )

    # DurationNanos: tag an int64 as a duration (semantic cast). The F
    # overload truncates (px.DurationNanos(px.floor(...)) in service_stats).
    for arg_t in (I, F, T):
        r.register_scalar(
            ScalarUDF(
                "DurationNanos",
                (arg_t,),
                I,
                lambda x: x.astype(jnp.int64) if hasattr(x, "astype") else x,
                Executor.DEVICE,
                out_semantic=SemanticType.ST_DURATION_NS,
            )
        )
    # Time: int64 -> TIME64NS cast.
    r.register_scalar(
        ScalarUDF(
            "Time",
            (I,),
            T,
            lambda x: x,
            Executor.DEVICE,
            out_semantic=SemanticType.ST_TIME_NS,
        )
    )
