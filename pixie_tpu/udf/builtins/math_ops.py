"""Arithmetic/comparison scalar UDFs and the core aggregate UDAs.

Ref: src/carnot/funcs/builtins/math_ops.h — MeanUDA (:585), SumUDA (:631),
MaxUDA (:663), MinUDA (:705), CountUDA (:746) and the scalar arithmetic
templates. TPU re-design: scalars are jnp elementwise lambdas (XLA fuses them
into neighbors); UDAs are masked segment reductions from pixie_tpu.ops with
[num_groups]-shaped states and PSUM/PMAX/PMIN merge contracts.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from pixie_tpu.ops import segment
from pixie_tpu.types import DataType, SemanticType
from pixie_tpu.udf.registry import Registry
from pixie_tpu.udf.udf import UDA, Executor, MergeKind, ScalarUDF

F = DataType.FLOAT64
I = DataType.INT64
B = DataType.BOOLEAN
S = DataType.STRING
T = DataType.TIME64NS


def _preserve_first(sems):
    return sems[0] if sems else SemanticType.ST_NONE


_INT_DIV_SAFE = lambda a, b: jnp.where(b != 0, a / jnp.where(b == 0, 1, b), 0.0)


def register(r: Registry) -> None:
    # -- binary arithmetic (device) ---------------------------------------
    table = [
        ("add", lambda a, b: a + b, [((F, F), F), ((I, I), I)]),
        ("subtract", lambda a, b: a - b, [((F, F), F), ((I, I), I)]),
        ("multiply", lambda a, b: a * b, [((F, F), F), ((I, I), I)]),
        # divide always returns float (ref: math_ops.h division semantics);
        # guarded against div-by-zero which would trap row batches.
        ("divide", _INT_DIV_SAFE, [((F, F), F), ((I, I), F)]),
        ("modulo", lambda a, b: jnp.where(b != 0, a % jnp.where(b == 0, 1, b), 0),
         [((I, I), I), ((F, F), F)]),
        ("pow", lambda a, b: jnp.power(a, b), [((F, F), F)]),
        ("logical_and", lambda a, b: a & b, [((B, B), B)]),
        ("logical_or", lambda a, b: a | b, [((B, B), B)]),
    ]
    for name, fn, sigs in table:
        for args, out in sigs:
            r.register_scalar(
                ScalarUDF(name, args, out, fn, Executor.DEVICE,
                          out_semantic=_preserve_first)
            )

    # -- comparisons (device; string comparisons resolve via dictionary
    #    codes in the expression evaluator before reaching these) ----------
    cmps = [
        ("equal", lambda a, b: a == b),
        ("notEqual", lambda a, b: a != b),
        ("lessThan", lambda a, b: a < b),
        ("lessThanEqual", lambda a, b: a <= b),
        ("greaterThan", lambda a, b: a > b),
        ("greaterThanEqual", lambda a, b: a >= b),
    ]
    for name, fn in cmps:
        for args in [(F, F), (I, I), (B, B), (T, T)]:
            r.register_scalar(ScalarUDF(name, args, B, fn, Executor.DEVICE))
    # code-space equality for strings (codes are comparable within a dict)
    for name, fn in cmps[:2]:
        r.register_scalar(ScalarUDF(name, (S, S), B, fn, Executor.DEVICE))

    # -- unary (device) ----------------------------------------------------
    unary = [
        ("negate", lambda a: -a, [(F, F), (I, I)]),
        ("logical_not", lambda a: ~a, [(B, B)]),
        ("abs", jnp.abs, [(F, F), (I, I)]),
        ("ceil", lambda a: jnp.ceil(a).astype(jnp.int64), [(F, I)]),
        ("floor", lambda a: jnp.floor(a).astype(jnp.int64), [(F, I)]),
        ("round", lambda a: jnp.round(a).astype(jnp.int64), [(F, I)]),
        ("ln", jnp.log, [(F, F)]),
        ("log2", jnp.log2, [(F, F)]),
        ("log10", jnp.log10, [(F, F)]),
        ("exp", jnp.exp, [(F, F)]),
        ("sqrt", jnp.sqrt, [(F, F)]),
    ]
    for name, fn, sigs in unary:
        for arg, out in sigs:
            r.register_scalar(
                ScalarUDF(name, (arg,), out, fn, Executor.DEVICE,
                          out_semantic=_preserve_first)
            )
    r.register_scalar(
        ScalarUDF("log", (F, F), F, lambda b, x: jnp.log(x) / jnp.log(b),
                  Executor.DEVICE)
    )

    # -- UDAs --------------------------------------------------------------
    def count_uda(arg_t):
        return UDA(
            name="count",
            arg_types=(arg_t,),
            out_type=I,
            init=lambda g: jnp.zeros((g,), jnp.int64),
            update=lambda st, gids, col, mask=None: st
            + segment.seg_count(gids, st.shape[0], mask),
            merge=lambda a, b: a + b,
            finalize=lambda st: st,
            merge_kind=MergeKind.PSUM,
            reads_args=False,  # counts rows; never reads the column
            fused_rows=lambda col, mask: [mask.astype(jnp.float32)],
            fused_apply=lambda st, t: st + t[0].astype(jnp.int64),
            doc="Number of rows in the group.",
        )

    for t in (F, I, S, B, T):
        r.register_uda(count_uda(t))

    def sum_uda(arg_t, out_t, acc_dtype):
        if acc_dtype == jnp.int64:
            if arg_t == B:
                # Bool sums are counts of trues: one f32 row suffices.
                fused_rows = lambda col, mask: [
                    (col & mask).astype(jnp.float32)
                ]
                fused_apply = lambda st, t: st + t[0].astype(jnp.int64)
            else:
                fused_rows = lambda col, mask: segment.limb_rows_i64(
                    jnp.where(mask, col.astype(jnp.int64), 0)
                )
                fused_apply = lambda st, t: st + segment.reconstruct_i64(t)
        else:
            fused_rows = fused_apply = None  # f64 keeps its own chunked path
        return UDA(
            name="sum",
            arg_types=(arg_t,),
            out_type=out_t,
            init=lambda g: jnp.zeros((g,), acc_dtype),
            update=lambda st, gids, col, mask=None: st
            + segment.seg_sum(col.astype(acc_dtype), gids, st.shape[0], mask),
            merge=lambda a, b: a + b,
            finalize=lambda st: st,
            merge_kind=MergeKind.PSUM,
            out_semantic=_preserve_first,
            fused_rows=fused_rows,
            fused_apply=fused_apply,
            doc="Sum of the column within the group.",
        )

    r.register_uda(sum_uda(F, F, jnp.float64))
    r.register_uda(sum_uda(I, I, jnp.int64))
    r.register_uda(sum_uda(B, I, jnp.int64))

    def mean_uda(arg_t):
        return UDA(
            name="mean",
            arg_types=(arg_t,),
            out_type=F,
            init=lambda g: {
                "sum": jnp.zeros((g,), jnp.float64),
                "count": jnp.zeros((g,), jnp.int64),
            },
            update=lambda st, gids, col, mask=None: {
                "sum": st["sum"]
                + segment.seg_sum(
                    col.astype(jnp.float64), gids, st["sum"].shape[0], mask
                ),
                "count": st["count"]
                + segment.seg_count(gids, st["count"].shape[0], mask),
            },
            merge=lambda a, b: {
                "sum": a["sum"] + b["sum"],
                "count": a["count"] + b["count"],
            },
            finalize=lambda st: st["sum"] / jnp.maximum(st["count"], 1),
            merge_kind=MergeKind.PSUM,
            out_semantic=_preserve_first,
            doc="Arithmetic mean (sum/count pair state; merge-safe).",
        )

    r.register_uda(mean_uda(F))

    def minmax_uda(name, arg_t, is_min):
        seg_fn = segment.seg_min if is_min else segment.seg_max
        dtype = jnp.float64 if arg_t == F else jnp.int64
        ident = (
            jnp.array(jnp.inf if is_min else -jnp.inf, dtype)
            if arg_t == F
            else jnp.array(
                jnp.iinfo(jnp.int64).max if is_min else jnp.iinfo(jnp.int64).min,
                dtype,
            )
        )
        pick = jnp.minimum if is_min else jnp.maximum

        def fin(st):
            return jnp.where(st == ident, jnp.zeros_like(st), st)

        return UDA(
            name=name,
            arg_types=(arg_t,),
            out_type=arg_t,
            init=lambda g: jnp.full((g,), ident, dtype),
            update=lambda st, gids, col, mask=None: pick(
                st, seg_fn(col.astype(dtype), gids, st.shape[0], mask)
            ),
            merge=pick,
            finalize=fin,
            merge_kind=MergeKind.PMIN if is_min else MergeKind.PMAX,
            out_semantic=_preserve_first,
            # min/max have no MXU einsum form; seg_min/seg_max route
            # high-cardinality blocks through the r8 sort–compact lane
            # (two-operand sort + O(groups) scatter) above
            # segment.SORTED_MIN_ROWS instead of the scalar scatter.
            doc=f"{'Minimum' if is_min else 'Maximum'} value in the group.",
        )

    for arg_t in (F, I):
        r.register_uda(minmax_uda("min", arg_t, True))
        r.register_uda(minmax_uda("max", arg_t, False))

    def var_state_uda(name, finalize):
        return UDA(
            name=name,
            arg_types=(F,),
            out_type=F,
            init=lambda g: {
                "n": jnp.zeros((g,), jnp.int64),
                "sum": jnp.zeros((g,), jnp.float64),
                "sumsq": jnp.zeros((g,), jnp.float64),
            },
            update=lambda st, gids, col, mask=None: {
                "n": st["n"] + segment.seg_count(gids, st["n"].shape[0], mask),
                "sum": st["sum"]
                + segment.seg_sum(col, gids, st["sum"].shape[0], mask),
                "sumsq": st["sumsq"]
                + segment.seg_sum(col * col, gids, st["sumsq"].shape[0], mask),
            },
            merge=lambda a, b: {k: a[k] + b[k] for k in a},
            finalize=finalize,
            merge_kind=MergeKind.PSUM,
            doc="Moment-based dispersion aggregate.",
        )

    def _var(st):
        n = jnp.maximum(st["n"].astype(jnp.float64), 1.0)
        v = st["sumsq"] / n - (st["sum"] / n) ** 2
        return jnp.maximum(v, 0.0)

    r.register_uda(var_state_uda("variance", _var))
    r.register_uda(var_state_uda("stddev", lambda st: jnp.sqrt(_var(st))))
