"""JSON scalar UDFs (ref: src/carnot/funcs/builtins/json_ops.h — PluckUDF,
PluckAsInt64UDF, PluckAsFloat64UDF). Host-executed, dictionary-compatible:
quantile JSON columns have one distinct value per group, so plucks cost one
json parse per group."""

from __future__ import annotations

import json

import numpy as np

from pixie_tpu.types import DataType
from pixie_tpu.udf.registry import Registry
from pixie_tpu.udf.udf import Executor, ScalarUDF

S = DataType.STRING
I = DataType.INT64
F = DataType.FLOAT64


def _pluck(default, cast):
    def fn(col, key):
        n = len(col)
        keys = key if isinstance(key, np.ndarray) else None
        out = np.empty(n, dtype=object if cast is str else np.float64)
        if cast is int:
            out = np.empty(n, dtype=np.int64)
        for i in range(n):
            k = keys[i] if keys is not None else key
            try:
                v = json.loads(col[i])[k]
                out[i] = cast(v)
            except (ValueError, KeyError, TypeError):
                out[i] = default
        return out

    return fn


def register(r: Registry) -> None:
    r.register_scalar(
        ScalarUDF("pluck", (S, S), S, _pluck("", str), Executor.HOST,
                  dict_compatible=True)
    )
    r.register_scalar(
        ScalarUDF("pluck_int64", (S, S), I, _pluck(0, int), Executor.HOST,
                  dict_compatible=True)
    )
    r.register_scalar(
        ScalarUDF("pluck_float64", (S, S), F, _pluck(float("nan"), float),
                  Executor.HOST, dict_compatible=True)
    )
