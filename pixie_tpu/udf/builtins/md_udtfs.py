"""Metadata/introspection UDTFs.

Ref: src/vizier/funcs/md_udtfs/md_udtfs.h — GetAgentStatus, table info,
and UDF-list UDTFs served from the vizier service context; here they read
the FunctionContext's vizier_ctx / table_store / registry
(exec/exec_state.py). PxL usage is unchanged:
``px.display(px.GetAgentStatus())``.
"""

from __future__ import annotations


from pixie_tpu.types import DataType, Relation
from pixie_tpu.udf.registry import Registry
from pixie_tpu.udf.udf import UDTF

S = DataType.STRING
I = DataType.INT64
B = DataType.BOOLEAN
T = DataType.TIME64NS


def _agent_rows(ctx) -> list[dict]:
    vc = ctx.vizier_ctx
    if vc is not None and hasattr(vc, "agents"):
        return list(vc.agents())
    # Standalone engine: report the single local instance.
    md = ctx.metadata_state
    return [
        {
            "agent_id": "local",
            "asid": getattr(md, "asid", 0) if md is not None else 0,
            "hostname": (
                getattr(md, "hostname", "localhost")
                if md is not None
                else "localhost"
            ),
            "agent_state": "AGENT_STATE_HEALTHY",
            "last_heartbeat_ns": 0,
            "kelvin": False,
        }
    ]


def register(r: Registry) -> None:
    def get_agent_status(ctx):
        rows = _agent_rows(ctx)
        return {
            "agent_id": [a.get("agent_id", "") for a in rows],
            "asid": [int(a.get("asid", 0)) for a in rows],
            "hostname": [a.get("hostname", "") for a in rows],
            "agent_state": [
                a.get("agent_state", "AGENT_STATE_HEALTHY") for a in rows
            ],
            "last_heartbeat_ns": [
                # elapsed ns since heartbeat (duration, not wall clock)
                int(a.get("last_heartbeat_ns", 0)) for a in rows
            ],
            "kelvin": [bool(a.get("kelvin", False)) for a in rows],
        }

    r.register_udtf(
        UDTF(
            name="GetAgentStatus",
            arg_spec={},
            fn=get_agent_status,
            output_relation=Relation.of(
                ("agent_id", S),
                ("asid", I),
                ("hostname", S),
                ("agent_state", S),
                ("last_heartbeat_ns", I),
                ("kelvin", B),
            ),
            doc="Status of every agent in the cluster (md_udtfs.h "
            "GetAgentStatus).",
        )
    )

    def get_table_status(ctx):
        names, batches, rows, bytes_, min_t, max_t = [], [], [], [], [], []
        store = ctx.table_store
        for name in sorted(store.table_names()) if store else []:
            t = store.get_table(name)
            st = t.stats()
            names.append(name)
            batches.append(int(st.num_batches))
            rows.append(int(st.num_rows))
            bytes_.append(int(st.bytes))
            tmin, tmax = t.time_bounds()
            min_t.append(int(tmin if tmin is not None else 0))
            max_t.append(int(tmax if tmax is not None else 0))
        return {
            "table_name": names,
            "num_batches": batches,
            "num_rows": rows,
            "size_bytes": bytes_,
            "min_time": min_t,
            "max_time": max_t,
        }

    r.register_udtf(
        UDTF(
            name="GetTableStatus",
            arg_spec={},
            fn=get_table_status,
            output_relation=Relation.of(
                ("table_name", S),
                ("num_batches", I),
                ("num_rows", I),
                ("size_bytes", I),
                ("min_time", T),
                ("max_time", T),
            ),
            doc="Occupancy of every table in this agent's table store "
            "(md_udtfs table-info UDTF).",
        )
    )

    def get_tables(ctx):
        store = ctx.table_store
        names = sorted(store.table_names()) if store else []
        return {
            "table_name": names,
            "table_desc": ["" for _ in names],
        }

    r.register_udtf(
        UDTF(
            name="GetTables",
            arg_spec={},
            fn=get_tables,
            output_relation=Relation.of(
                ("table_name", S), ("table_desc", S)
            ),
            doc="Data tables available to query "
            "(md_udtfs_impl.h GetTables, px/schemas).",
        )
    )

    def get_schemas(ctx):
        store = ctx.table_store
        tn, cn, ct, pt, cd = [], [], [], [], []
        for name in sorted(store.table_names()) if store else []:
            rel = store.get_relation(name)
            for col in rel:
                tn.append(name)
                cn.append(col.name)
                ct.append(col.data_type.name)
                pt.append("GENERAL")
                cd.append("")
        return {
            "table_name": tn,
            "column_name": cn,
            "column_type": ct,
            "pattern_type": pt,
            "column_desc": cd,
        }

    r.register_udtf(
        UDTF(
            name="GetSchemas",
            arg_spec={},
            fn=get_schemas,
            output_relation=Relation.of(
                ("table_name", S),
                ("column_name", S),
                ("column_type", S),
                ("pattern_type", S),
                ("column_desc", S),
            ),
            doc="Column schemas of every table "
            "(md_udtfs_impl.h GetTableSchemas / px.GetSchemas).",
        )
    )

    def get_udf_list(ctx):
        reg = ctx.registry
        names, kinds, args, rets = [], [], [], []
        if reg is not None:
            for key, udf in sorted(
                reg._scalars.items(), key=lambda kv: kv[0].name
            ):
                names.append(key.name)
                kinds.append("scalar")
                args.append(",".join(t.name for t in key.arg_types))
                rets.append(udf.out_type.name)
            for key, uda in sorted(
                reg._udas.items(), key=lambda kv: kv[0].name
            ):
                names.append(key.name)
                kinds.append("uda")
                args.append(",".join(t.name for t in key.arg_types))
                rets.append(uda.out_type.name)
            for name, udtf in sorted(reg._udtfs.items()):
                names.append(name)
                kinds.append("udtf")
                args.append(",".join(udtf.arg_spec))
                rets.append("table")
        return {
            "name": names,
            "kind": kinds,
            "arg_types": args,
            "return_type": rets,
        }

    r.register_udtf(
        UDTF(
            name="GetUDFList",
            arg_spec={},
            fn=get_udf_list,
            output_relation=Relation.of(
                ("name", S),
                ("kind", S),
                ("arg_types", S),
                ("return_type", S),
            ),
            doc="Every registered scalar/UDA/UDTF with its signature "
            "(md_udtfs GetUDFList/GetUDAList collapsed).",
        )
    )
