"""Typed UDF/UDA/UDTF framework.

Ref: src/carnot/udf/ (ScalarUDF udf.h:78, UDA udf.h:104, Registry
registry.h:101, vectorized exec udf_wrapper.h, UDTF udtf.h). TPU re-design:
scalar UDFs are vectorized jax-traceable functions over whole columns (the
reference's row-at-a-time Exec + its column-wise wrapper collapse into one
thing); UDAs are pytree sketch states with init/update/merge/finalize where
update folds a whole masked batch of (group-id, value) rows at once and merge
is the cross-shard collective contract (psum/pmax for elementwise states,
all-gather + tree-merge otherwise).
"""

from pixie_tpu.udf.udf import (  # noqa: F401
    UDA,
    UDTF,
    Executor,
    MergeKind,
    ScalarUDF,
)
from pixie_tpu.udf.registry import Registry, RegistryKey, default_registry  # noqa: F401
