"""Splitter + coordinator: logical plan → per-agent distributed plan.

Ref: splitter/splitter.h:52,111 (cut at blocking ops),
partial_op_mgr.h:36,77,94 (partial-agg rewrite when UDAs serialize — all of
ours do by construction), coordinator/coordinator.h:47,86 (fragment→agent
assignment + source pruning).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from pixie_tpu.plan.operators import (
    AggOp,
    AggStage,
    BridgeSinkOp,
    BridgeSourceOp,
    JoinOp,
    LimitOp,
    MapOp,
    FilterOp,
    MemorySourceOp,
    Operator,
    UnionOp,
)
from pixie_tpu.plan.plan import Plan, PlanFragment
from pixie_tpu.types import Relation


@dataclasses.dataclass(frozen=True)
class AgentInfo:
    """One data-bearing engine instance (ref: distributedpb CarnotInfo)."""

    agent_id: str
    tables: frozenset  # table names this agent holds locally
    is_kelvin: bool = False


@dataclasses.dataclass
class DistributedState:
    """Topology snapshot the coordinator plans against
    (ref: coordinator.h DistributedState; broker tracker supplies it)."""

    agents: list[AgentInfo]

    def pems(self) -> list[AgentInfo]:
        return [a for a in self.agents if not a.is_kelvin]

    def kelvin(self) -> Optional[AgentInfo]:
        for a in self.agents:
            if a.is_kelvin:
                return a
        return None


class DistributedPlanner:
    """Plan(logical_plan, state) → distributed Plan with per-agent
    fragments wired through bridges (ref: distributed_planner.h:65-83)."""

    def __init__(self, registry, table_relations: dict[str, Relation]):
        self.registry = registry
        self.table_relations = dict(table_relations)

    def plan(self, logical: Plan, state: DistributedState) -> Plan:
        (frag,) = logical.fragments  # compiler emits one logical fragment
        kelvin = state.kelvin()
        if kelvin is None:
            raise ValueError("distributed planning requires a kelvin agent")

        source_tables = {
            frag.node(n).table_name
            for n in frag.nodes()
            if isinstance(frag.node(n), MemorySourceOp)
        }
        # Source pruning (prune_unavailable_sources_rule): only agents
        # holding every needed table run the pre-blocking fragment.
        pems = [
            a for a in state.pems() if source_tables <= set(a.tables)
        ]
        if not pems:
            raise ValueError(
                f"no agent holds tables {sorted(source_tables)}"
            )

        cut = self._find_cut(frag)
        out = Plan(logical.query_id)
        if cut is None:
            # No blocking agg on a single source chain: PEMs run everything
            # up to the sinks' parents and forward rows; Kelvin unions and
            # runs the sinks (plus any blocking ops like join/limit).
            self._split_forwarding(frag, out, pems, kelvin)
        else:
            self._split_partial_agg(frag, cut, out, pems, kelvin)
        return out

    # -- cut discovery ------------------------------------------------------
    def _find_cut(self, frag: PlanFragment) -> Optional[int]:
        """The blocking agg to cut at: a FULL non-windowed AggOp whose
        ancestors are a single-source map/filter chain (the shape
        partial_op_mgr rewrites). Joins/unions upstream disable the
        partial-agg split (ref: splitter falls back to plain cut)."""
        for nid in frag.topo_order():
            op = frag.node(nid)
            if not (
                isinstance(op, AggOp)
                and op.stage == AggStage.FULL
                and not op.windowed
            ):
                continue
            cur = nid
            ok = True
            while True:
                parents = frag.parents(cur)
                if len(parents) != 1:
                    ok = False
                    break
                cur = parents[0]
                pop = frag.node(cur)
                if isinstance(pop, MemorySourceOp):
                    break
                if not isinstance(pop, (MapOp, FilterOp)):
                    ok = False
                    break
            if ok:
                return nid
        return None

    # -- partial-agg split (partial_op_mgr.h:94) ----------------------------
    def _split_partial_agg(
        self, frag: PlanFragment, agg_nid: int, out: Plan, pems, kelvin
    ) -> None:
        agg_op: AggOp = frag.node(agg_nid)
        bridge_id = f"agg-{agg_nid}"
        ancestors = self._ancestors(frag, agg_nid)
        rels = frag.resolve_relations(
            self.registry, lambda op: self.table_relations[op.table_name]
        )
        pre_agg_rel = rels[frag.parents(agg_nid)[0]]

        # Per-PEM fragment: chain → Agg(PARTIAL) → BridgeSink.
        for a in pems:
            f = out.add_fragment(instance=a.agent_id)
            mapping: dict[int, int] = {}
            for nid in frag.topo_order():
                if nid not in ancestors:
                    continue
                mapping[nid] = f.add(
                    frag.node(nid), [mapping[p] for p in frag.parents(nid)]
                )
            partial = f.add(
                dataclasses.replace(agg_op, stage=AggStage.PARTIAL),
                [mapping[frag.parents(agg_nid)[0]]],
            )
            f.add(BridgeSinkOp(bridge_id), [partial])

        # Kelvin fragment: BridgeSource → Agg(MERGE) → suffix.
        kf = out.add_fragment(instance=kelvin.agent_id)
        merge_in_rel = agg_op.merge_input_relation(pre_agg_rel)
        bsrc = kf.add(BridgeSourceOp(bridge_id, merge_in_rel))
        merge = kf.add(
            dataclasses.replace(
                agg_op, stage=AggStage.MERGE, pre_agg_relation=pre_agg_rel
            ),
            [bsrc],
        )
        mapping = {agg_nid: merge}
        for nid in frag.topo_order():
            if nid == agg_nid or nid in ancestors:
                continue
            mapping[nid] = kf.add(
                frag.node(nid), [mapping[p] for p in frag.parents(nid)]
            )

    # -- plain forwarding split (no partial-able agg) -----------------------
    def _split_forwarding(
        self, frag: PlanFragment, out: Plan, pems, kelvin
    ) -> None:
        """PEMs run the non-blocking prefix of each source chain and forward
        rows; Kelvin runs blocking ops (join/union/limit/agg) + sinks.

        The cut line: a node stays on the PEM side while it is a
        MemorySource or a Map/Filter with a single parent on the PEM side.
        Everything else (joins, unions, aggs over multi-parent shapes,
        limits, sinks) runs on Kelvin (ref: splitter.h blocking-op cut).
        """
        pem_side: set[int] = set()
        for nid in frag.topo_order():
            op = frag.node(nid)
            parents = frag.parents(nid)
            if isinstance(op, MemorySourceOp):
                pem_side.add(nid)
            elif (
                isinstance(op, (MapOp, FilterOp))
                and len(parents) == 1
                and parents[0] in pem_side
                and len(frag.children(parents[0])) == 1
            ):
                pem_side.add(nid)
        # Boundary nodes: pem-side nodes with a consumer off the pem side
        # (or that are sinks' parents).
        boundary = [
            nid for nid in pem_side
            if any(c not in pem_side for c in frag.children(nid))
        ]
        rels = frag.resolve_relations(
            self.registry, lambda op: self.table_relations[op.table_name]
        )
        for a in pems:
            f = out.add_fragment(instance=a.agent_id)
            mapping: dict[int, int] = {}
            for nid in frag.topo_order():
                if nid not in pem_side:
                    continue
                mapping[nid] = f.add(
                    frag.node(nid), [mapping[p] for p in frag.parents(nid)]
                )
            for b in boundary:
                f.add(BridgeSinkOp(f"fwd-{b}"), [mapping[b]])
        kf = out.add_fragment(instance=kelvin.agent_id)
        mapping = {}
        for b in boundary:
            mapping[b] = kf.add(BridgeSourceOp(f"fwd-{b}", rels[b]))
        for nid in frag.topo_order():
            if nid in pem_side:
                continue
            mapping[nid] = kf.add(
                frag.node(nid), [mapping[p] for p in frag.parents(nid)]
            )

    @staticmethod
    def _ancestors(frag: PlanFragment, nid: int) -> set:
        out: set[int] = set()
        stack = list(frag.parents(nid))
        while stack:
            p = stack.pop()
            if p not in out:
                out.add(p)
                stack.extend(frag.parents(p))
        return out
