"""Mesh geometry + partition rules for multi-host fold execution.

Ref: the SNIPPETS.md pjit/shard_map exemplars — a partition-rule tree
(regex → ``PartitionSpec``) resolved per named array, plus small
helpers that wrap a ``jax.sharding.Mesh`` into per-array
``NamedSharding``s. This module is the single source of truth for
mesh *geometry*: axis names and sizes are declared here, carried into
every r7 program signature (``MeshConfig.signature()``), and used by
the staging layer to place blocks/masks/gids across ALL mesh axes
while aux/LUT/env values replicate.

Geometry model: the mesh is a tuple of named axes, outermost first.
A flat single-host mesh is ``d:<ndev>`` — the 1-host special case.
A simulated (or real) multi-host mesh prefixes a ``hosts`` axis, e.g.
``hosts:2,d:4``. Data arrays shard their leading (device) dimension
over the *full* axis tuple; collectives reduce/gather over the full
tuple, which is bit-identical to the flat mesh because XLA's
row-major device order makes ``all_gather(x, ("hosts", "d"))`` and a
fused ``psum(x, ("hosts", "d"))`` coincide with their flat-axis
counterparts (verified under --xla_force_host_platform_device_count).
The ``hosts`` axis only changes behavior where code *asks* for it:
the partitioned join gathers within ``inner_axes()`` (per-host) and
concatenates shard outputs across ``host_axis()``.
"""

from __future__ import annotations

import dataclasses
import math
import re
from typing import Optional, Sequence

import numpy as np

from pixie_tpu.utils import flags


class MeshGeometryError(RuntimeError):
    """A mesh-geometry failure the executor can recover from (r23).

    ``kind`` drives the recovery policy in ``MeshExecutor``:

    - ``host_loss`` / ``collective_timeout`` — the current geometry is
      suspect; re-plan the fold onto the next degradation rung
      (``MeshConfig.degrade``), bit-identical by the r21 invariant.
    - ``checkpoint_corrupt`` — a window checkpoint read back bad;
      discard it and refold from scratch on the surviving geometry
      (r14 RingSpill posture: never resurrect corrupt state).
    - ``signature_mismatch`` — a cached program's geometry disagrees
      with the executor's; caller error, routed straight to the host
      engine fallback (no degrade retry — the geometry itself is fine).
    """

    KINDS = (
        "host_loss",
        "collective_timeout",
        "checkpoint_corrupt",
        "signature_mismatch",
    )

    def __init__(self, kind: str, detail: str = ""):
        assert kind in self.KINDS, kind
        super().__init__(
            f"mesh geometry failure [{kind}]" + (f": {detail}" if detail else "")
        )
        self.kind = kind
        self.detail = detail

    @property
    def recoverable(self) -> bool:
        """True iff retrying on a degraded geometry can help."""
        return self.kind in ("host_loss", "collective_timeout")


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Declarative mesh geometry: ((axis_name, size), ...) outermost first."""

    axes: tuple  # tuple[tuple[str, int], ...]

    def __post_init__(self):
        if not self.axes:
            raise ValueError("MeshConfig needs at least one axis")
        names = [n for n, _ in self.axes]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate mesh axis names: {names}")
        for name, size in self.axes:
            if not isinstance(size, int) or size < 1:
                raise ValueError(f"bad mesh axis {name}:{size}")

    @property
    def names(self) -> tuple:
        return tuple(n for n, _ in self.axes)

    @property
    def shape(self) -> tuple:
        return tuple(s for _, s in self.axes)

    @property
    def total_devices(self) -> int:
        return int(math.prod(self.shape))

    def signature(self) -> str:
        """Geometry string embedded in r7 program signatures."""
        return ",".join(f"{n}:{s}" for n, s in self.axes)

    @staticmethod
    def flat(ndev: int) -> "MeshConfig":
        return MeshConfig(axes=(("d", int(ndev)),))

    @staticmethod
    def parse(spec: str, ndev: int) -> "MeshConfig":
        """Parse 'hosts:2,d:4' (one size may be -1 = fill remaining)."""
        spec = (spec or "").strip()
        if not spec:
            return MeshConfig.flat(ndev)
        axes = []
        for part in spec.split(","):
            part = part.strip()
            m = re.fullmatch(r"([A-Za-z_][A-Za-z0-9_]*)\s*:\s*(-?\d+)", part)
            if not m:
                raise ValueError(f"bad mesh axis spec {part!r} in {spec!r}")
            axes.append((m.group(1), int(m.group(2))))
        wild = [i for i, (_, s) in enumerate(axes) if s == -1]
        if len(wild) > 1:
            raise ValueError(f"at most one -1 axis allowed: {spec!r}")
        if wild:
            known = math.prod(s for _, s in axes if s != -1)
            if known <= 0 or ndev % known:
                raise ValueError(
                    f"mesh {spec!r} does not divide {ndev} devices"
                )
            name, _ = axes[wild[0]]
            axes[wild[0]] = (name, ndev // known)
        cfg = MeshConfig(axes=tuple(axes))
        if cfg.total_devices != ndev:
            raise ValueError(
                f"mesh {spec!r} wants {cfg.total_devices} devices, "
                f"have {ndev}"
            )
        return cfg

    @staticmethod
    def of_mesh(mesh) -> "MeshConfig":
        """Derive the config of an existing jax Mesh."""
        return MeshConfig(
            axes=tuple(zip(tuple(mesh.axis_names), tuple(mesh.devices.shape)))
        )

    @staticmethod
    def from_flags(ndev: int) -> "MeshConfig":
        return MeshConfig.parse(flags.mesh_axes, ndev)

    def degrade(self, lost_hosts: int = 1) -> "Optional[MeshConfig]":
        """Best surviving geometry after losing ``lost_hosts`` hosts (r23).

        The simulated runtime keeps every local device; "losing a host"
        is a trust statement about the outermost axis, so each rung
        preserves ``total_devices`` and refolds the freed devices into
        the innermost axis — which is exactly what keeps the answer
        bit-identical (r21: any factorization of the same device set
        folds bit-for-bit the same). Ladder shape for hosts:4,d:2 →
        hosts:2,d:4 → d:8 → None (None = host engine, past the mesh).

        A flat (single-axis) mesh has no hosts to shed: returns None.
        The surviving host count is the largest divisor of
        ``total_devices`` that is < the current host count and
        <= hosts - lost_hosts; if none >= 2 exists, collapse to flat.
        """
        if len(self.axes) < 2:
            return None
        hosts = self.shape[0]
        ndev = self.total_devices
        want = hosts - max(1, int(lost_hosts))
        survivors = 0
        for h in range(min(want, hosts - 1), 1, -1):
            if ndev % h == 0:
                survivors = h
                break
        if survivors < 2:
            return MeshConfig.flat(ndev)
        inner = list(self.axes[1:])
        others = math.prod(s for _, s in inner[:-1])
        per_host = ndev // survivors
        if per_host % others:
            # The surviving per-host share no longer factors through the
            # middle axes: flatten everything inner into the last axis.
            return MeshConfig(
                axes=((self.axes[0][0], survivors), (inner[-1][0], per_host))
            )
        inner[-1] = (inner[-1][0], per_host // others)
        return MeshConfig(axes=((self.axes[0][0], survivors), *inner))

    def ladder(self) -> "list[Optional[MeshConfig]]":
        """Full degradation ladder, this geometry first, ``None`` (host
        engine) last. Each rung is one ``degrade()`` step; the list is
        what the executor's per-geometry breaker walks."""
        rungs: "list[Optional[MeshConfig]]" = [self]
        cur: "Optional[MeshConfig]" = self
        while cur is not None:
            cur = cur.degrade()
            if cur is not None and cur.signature() == rungs[-1].signature():
                break
            rungs.append(cur)
        if rungs[-1] is not None:
            rungs.append(None)
        return rungs

    def build(self, devices: Optional[Sequence] = None):
        """Materialize a jax.sharding.Mesh with this geometry."""
        import jax
        from jax.sharding import Mesh

        devs = np.array(list(devices) if devices is not None else jax.devices())
        if devs.size != self.total_devices:
            raise ValueError(
                f"mesh {self.signature()} wants {self.total_devices} "
                f"devices, have {devs.size}"
            )
        return Mesh(devs.reshape(self.shape), self.names)


def resolve_mesh(mesh=None, mesh_config: Optional[MeshConfig] = None):
    """(mesh, config) from whichever the caller has; flags fill gaps.

    - mesh given: config derived from it (explicit mesh wins).
    - config given: mesh built over all local devices.
    - neither: geometry comes from the ``mesh_axes`` flag (flat default).
    """
    import jax

    if mesh is not None:
        return mesh, MeshConfig.of_mesh(mesh)
    if mesh_config is None:
        mesh_config = MeshConfig.from_flags(len(jax.devices()))
    return mesh_config.build(), mesh_config


# ---------------------------------------------------------------------------
# Partition-rule helpers (SNIPPETS-style rule trees → per-array shardings)
# ---------------------------------------------------------------------------


def data_axes(mesh) -> tuple:
    """All mesh axis names, outermost first — the data-sharding tuple."""
    return tuple(mesh.axis_names)


def host_axis(mesh) -> str:
    """The outermost axis — shard boundary for partitioned work."""
    return tuple(mesh.axis_names)[0]


def inner_axes(mesh) -> tuple:
    """Axes within one host (empty on a 1-axis mesh)."""
    return tuple(mesh.axis_names)[1:]


def data_spec(mesh):
    """PartitionSpec sharding dim 0 over every mesh axis."""
    from jax.sharding import PartitionSpec as P

    return P(data_axes(mesh))


def data_sharding(mesh):
    from jax.sharding import NamedSharding

    return NamedSharding(mesh, data_spec(mesh))


def replicated_sharding(mesh):
    from jax.sharding import NamedSharding, PartitionSpec as P

    return NamedSharding(mesh, P())


# Default rule tree for staged fold inputs: blocks/mask/gids carry the
# row dimension and shard across the full mesh; everything else
# (env/LUT/aux/dictionary-derived values) replicates.
STAGED_PARTITION_RULES = (
    (r"(^|/)blocks(/|$)", "data"),
    (r"(^|/)mask$", "data"),
    (r"(^|/)gids$", "data"),
    (r"(^|/)(env|lut|aux|narrow|dict)(/|$)", "replicated"),
)


def match_partition_rules(rules, names, mesh):
    """Resolve each name through the rule tree → NamedSharding.

    First matching regex wins; unmatched names replicate (the safe
    default for scalars/aux, mirroring the SNIPPETS exemplar where
    unmatched leaves raise — here the fold's aux values are the
    common case, so replication is the correct fallback).
    """
    shardings = {}
    for name in names:
        kind = "replicated"
        for pattern, k in rules:
            if re.search(pattern, name):
                kind = k
                break
        shardings[name] = (
            data_sharding(mesh) if kind == "data" else replicated_sharding(mesh)
        )
    return shardings


__all__ = [
    "MeshConfig",
    "MeshGeometryError",
    "resolve_mesh",
    "data_axes",
    "host_axis",
    "inner_axes",
    "data_spec",
    "data_sharding",
    "replicated_sharding",
    "STAGED_PARTITION_RULES",
    "match_partition_rules",
]
