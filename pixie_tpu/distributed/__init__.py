"""Distributed planner: split logical plans across agents.

Ref: src/carnot/planner/distributed/ — Splitter::SplitKelvinAndAgents
(splitter/splitter.h:52,111) cuts the operator graph at blocking operators;
PartialOperatorMgr (partial_op_mgr/partial_op_mgr.h:36,77,94) rewrites
blocking aggregates into per-agent partial + merge stages; Coordinator
(coordinator/coordinator.h:47,86) assigns fragments to Carnot instances from
DistributedState and prunes agents without the needed tables
(prune_unavailable_sources_rule); the stitcher wires the GRPCSink→GRPCSource
bridges (distributed_stitcher_rules).

Two consumers:
- the multi-agent host path (PEM-role Carnots + a Kelvin-role Carnot over a
  BridgeRouter), exercised by the control plane in pixie_tpu.vizier;
- conceptually, the device-mesh pipeline (pixie_tpu.parallel) is this same
  split collapsed into one SPMD program — partial ≙ per-device scan, merge ≙
  ICI collective.
"""

from pixie_tpu.distributed.planner import (
    AgentInfo,
    DistributedPlanner,
    DistributedState,
)
from pixie_tpu.distributed.mesh import (
    MeshConfig,
    match_partition_rules,
    resolve_mesh,
)

__all__ = [
    "AgentInfo",
    "DistributedPlanner",
    "DistributedState",
    "MeshConfig",
    "match_partition_rules",
    "resolve_mesh",
]
