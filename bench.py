"""Benchmark: px/service_stats-class query throughput on TPU.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Metric: rows/sec/chip for the BASELINE config-2 query (groupby(service) ->
count + error-rate mean + latency quantile sketch) executed by the device
pipeline (pixie_tpu.parallel) over a synthetic http_events table staged in
HBM. Baseline target (BASELINE.md): 1e8 rows/sec/chip.

Steady-state protocol: the table is staged to the device once (the HBM cold
tier) and the query runs repeatedly; we report the best of N timed runs —
matching the reference's operator-benchmark methodology (table resident in
memory, query-time work measured;
/root/reference/src/carnot/blocking_agg_benchmark.cc).

Output correctness is asserted against HOST-computed truth accumulated
during data generation (exact per-service counts/error rates; quantiles
vs an independent numpy log-histogram within the sketches' documented
error) — a kernel bug that preserved row counts still fails the run.
"""

import json
import math
import os
import sys
import time

import numpy as np


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


# Host-truth latency histogram: log-spaced bins, ~0.7% relative bin width —
# an independent numpy implementation (np.digitize), NOT pixie_tpu's
# histogram op, so it cross-checks the device sketch rather than mirroring
# its bugs.
TRUTH_BINS = 4096
TRUTH_LO, TRUTH_HI = 1.0, 1e12
TRUTH_EDGES = np.logspace(
    math.log10(TRUTH_LO), math.log10(TRUTH_HI), TRUTH_BINS - 1
)


def truth_quantile(hist_row: np.ndarray, q: float) -> float:
    """Quantile from a log-histogram row using bin geometric midpoints."""
    total = hist_row.sum()
    if total == 0:
        return 0.0
    target = q * total
    cum = np.cumsum(hist_row)
    i = int(np.searchsorted(cum, target))
    i = min(i, TRUTH_BINS - 1)
    lo = TRUTH_EDGES[i - 1] if i >= 1 else TRUTH_LO
    hi = TRUTH_EDGES[i] if i < len(TRUTH_EDGES) else TRUTH_HI
    return math.sqrt(lo * hi)


def main() -> None:
    n_rows = int(os.environ.get("BENCH_ROWS", 256_000_000))
    n_services = int(os.environ.get("BENCH_SERVICES", 16))
    runs = int(os.environ.get("BENCH_RUNS", 5))

    import jax
    from jax.sharding import Mesh

    from pixie_tpu.engine import Carnot
    from pixie_tpu.parallel import MeshExecutor
    from pixie_tpu.types import DataType, Relation, SemanticType

    F, I, S, T = (
        DataType.FLOAT64,
        DataType.INT64,
        DataType.STRING,
        DataType.TIME64NS,
    )

    devices = jax.devices()
    n_chips = len(devices)
    mesh = Mesh(np.array(devices), ("d",))
    carnot = Carnot(
        device_executor=MeshExecutor(mesh=mesh, block_rows=1 << 21)
    )
    rel = Relation.of(
        ("time_", T, SemanticType.ST_TIME_NS),
        ("service", S, SemanticType.ST_SERVICE_NAME),
        ("resp_status", I),
        ("latency", F, SemanticType.ST_DURATION_NS),
    )
    table = carnot.table_store.create_table(
        "http_events", rel, size_limit=1 << 42
    )
    rng = np.random.default_rng(42)
    services = np.array(
        [f"ns/svc-{i}" for i in range(n_services)], dtype=object
    )
    # Host truth accumulators.
    true_count = np.zeros(n_services, np.int64)
    true_errors = np.zeros(n_services, np.int64)
    true_hist = np.zeros((n_services, TRUTH_BINS), np.int64)

    chunk = 8_000_000
    t_gen = time.perf_counter()
    for off in range(0, n_rows, chunk):
        m = min(chunk, n_rows - off)
        svc_idx = rng.integers(0, n_services, m)
        status = rng.choice(
            [200, 301, 404, 500], m, p=[0.85, 0.05, 0.05, 0.05]
        )
        latency = rng.exponential(3e7, m)
        table.write_pydict(
            {
                "time_": np.arange(off, off + m) * 1000,
                "service": services[svc_idx],
                "resp_status": status,
                "latency": latency,
            }
        )
        true_count += np.bincount(svc_idx, minlength=n_services)
        true_errors += np.bincount(
            svc_idx, weights=(status >= 400), minlength=n_services
        ).astype(np.int64)
        bins = np.digitize(latency, TRUTH_EDGES)
        true_hist += np.bincount(
            svc_idx * TRUTH_BINS + bins,
            minlength=n_services * TRUTH_BINS,
        ).reshape(n_services, TRUTH_BINS)
        log(f"generated {off + m}/{n_rows} rows")
    table.compact()
    table.stop()
    log(f"table built in {time.perf_counter() - t_gen:.1f}s")

    query = (
        "df = px.DataFrame(table='http_events')\n"
        "df.failure = df.resp_status >= 400\n"
        "stats = df.groupby(['service']).agg(\n"
        "    throughput=('time_', px.count),\n"
        "    error_rate=('failure', px.mean),\n"
        "    latency=('latency', px.quantiles),\n"
        ")\n"
        "px.display(stats, 'service_stats')\n"
    )

    # Warm-up: compile + stage (excluded, like the reference's benchmark
    # harness excludes table build).
    t_stage = time.perf_counter()
    result = carnot.execute_query(query)
    log(f"warm-up (compile+stage) in {time.perf_counter() - t_stage:.1f}s")

    def verify(result) -> None:
        rows = result.table("service_stats")
        by_svc = {
            s: i for i, s in enumerate(rows["service"])
        }
        assert len(by_svc) == n_services, f"got {len(by_svc)} groups"
        assert sum(rows["throughput"]) == n_rows, "row count mismatch"
        for j, name in enumerate(services):
            i = by_svc[name]
            assert rows["throughput"][i] == true_count[j], (
                name, rows["throughput"][i], true_count[j]
            )
            want_er = true_errors[j] / true_count[j]
            got_er = rows["error_rate"][i]
            assert abs(got_er - want_er) < 1e-9, (name, got_er, want_er)
            q = json.loads(rows["latency"][i])
            for key, qq in (("p50", 0.50), ("p99", 0.99)):
                want = truth_quantile(true_hist[j], qq)
                got = q[key]
                # sketch ~1.4% rel err + truth-bin ~0.7% -> 4% is decisive:
                # a wrong kernel is off by far more.
                assert abs(got - want) <= 0.04 * want, (
                    name, key, got, want
                )

    verify(result)

    best = float("inf")
    for _ in range(runs):
        t0 = time.perf_counter()
        result = carnot.execute_query(query)
        best = min(best, time.perf_counter() - t0)
    verify(result)

    rows_per_sec_per_chip = n_rows / best / n_chips
    baseline = 1e8  # BASELINE.md: >1e8 rows/sec/chip target
    print(
        json.dumps(
            {
                "metric": "service_stats_rows_per_sec_per_chip",
                "value": round(rows_per_sec_per_chip),
                "unit": "rows/s/chip",
                "vs_baseline": round(rows_per_sec_per_chip / baseline, 3),
            }
        )
    )


if __name__ == "__main__":
    sys.exit(main())
