"""Benchmarks for the five BASELINE configs.

Prints ONE JSON line (the headline metric: config-2 px/service_stats-class
throughput on TPU, target 1e8 rows/s/chip per BASELINE.md) and writes all
five configs' numbers to BENCH_DETAIL.json:

  1. http_data   — filter+project over http_events (host exec path).
  2. service_stats — groupby(service) count + error-rate + quantile sketch
     on the device pipeline (the headline; truth-checked).
  3. net_flow_graph — groupby(src,dst) byte-count sum + HLL distinct over
     conn_stats.
  4. perf_flamegraph — stack groupby + count merge over stack_traces.
  5. streaming sketches — t-digest + count-min over http_events latency
     with mesh sketch merge.

Steady-state protocol: tables are staged once (warm-up excluded); best of
N timed runs — the reference's operator-benchmark methodology
(/root/reference/src/carnot/blocking_agg_benchmark.cc). Config 2 output
correctness is asserted against HOST-computed truth accumulated during
generation (exact counts/error rates; quantiles vs an independent numpy
log-histogram), so a kernel bug that preserved row counts still fails.
Cold (first-query: compile + stage) latency is reported separately per
config alongside the warm steady-state number.

Regression gate: BENCH_DETAIL.json keeps each config's best-ever value;
any config regressing >10% vs its best marks the gate red (and the
headline line carries "gate": "red") so non-headline regressions cannot
ship silently. BENCH_GATE_SELFTEST=1 injects an impossible prior to
prove the gate trips.

Env knobs: BENCH_ROWS (configs 2/5; default 256M), BENCH_SMALL_ROWS
(configs 1/3/4; default 64M — large enough that the ~100ms tunnel fetch
round-trip does not dominate the steady-state metric), BENCH_RUNS,
BENCH_SERVICES, BENCH_CONFIGS (comma list, default "1,2,3,4,5"),
BENCH_BLOCK_ROWS (device block size).
"""

import json
import math
import os
import sys
import time

import numpy as np


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


GATE_TOLERANCE = 0.10  # >10% below best-ever trips the gate


def load_prior_best(path: str) -> dict:
    """metric name -> best-ever value from the ledger (accepts the old
    list format and the current dict format)."""
    try:
        with open(path) as f:
            prior = json.load(f)
    except (OSError, json.JSONDecodeError):
        return {}
    if isinstance(prior, list):  # r3 format
        return {
            e["metric"]: e["value"]
            for e in prior
            if "metric" in e and "value" in e
        }
    best = dict(prior.get("best", {}))
    for e in prior.get("configs", []):
        if "metric" in e and "value" in e:
            best[e["metric"]] = max(best.get(e["metric"], 0), e["value"])
    return best


def apply_gate(detail: list[dict], best: dict) -> dict:
    """Mark regressions >10% vs best-ever; returns the gate summary."""
    regressions = []
    for e in detail:
        prior = best.get(e["metric"])
        if prior and e["value"] < prior * (1 - GATE_TOLERANCE):
            e["regressed_vs_best"] = prior
            regressions.append(
                f"{e['metric']}: {e['value']:.3g} < best {prior:.3g}"
            )
    return {
        "status": "red" if regressions else "green",
        "regressions": regressions,
    }


# Host-truth latency histogram: log-spaced bins, ~0.7% relative bin width —
# an independent numpy implementation (np.digitize), NOT pixie_tpu's
# histogram op, so it cross-checks the device sketch rather than mirroring
# its bugs.
TRUTH_BINS = 4096
TRUTH_LO, TRUTH_HI = 1.0, 1e12
TRUTH_EDGES = np.logspace(
    math.log10(TRUTH_LO), math.log10(TRUTH_HI), TRUTH_BINS - 1
)


def truth_quantile(hist_row: np.ndarray, q: float) -> float:
    total = hist_row.sum()
    if total == 0:
        return 0.0
    cum = np.cumsum(hist_row)
    i = int(np.searchsorted(cum, q * total))
    i = min(i, TRUTH_BINS - 1)
    lo = TRUTH_EDGES[i - 1] if i >= 1 else TRUTH_LO
    hi = TRUTH_EDGES[i] if i < len(TRUTH_EDGES) else TRUTH_HI
    return math.sqrt(lo * hi)


def best_of(fn, runs: int):
    """(best wall-clock, last run's result) — so callers can verify a
    *timed* run's output instead of paying an extra execution."""
    best = float("inf")
    result = None
    for _ in range(runs):
        t0 = time.perf_counter()
        result = fn()
        best = min(best, time.perf_counter() - t0)
    return best, result


def main() -> None:
    n_rows = int(os.environ.get("BENCH_ROWS", 256_000_000))
    n_small = int(os.environ.get("BENCH_SMALL_ROWS", 64_000_000))
    n_services = int(os.environ.get("BENCH_SERVICES", 16))
    runs = int(os.environ.get("BENCH_RUNS", 5))
    block_rows = int(os.environ.get("BENCH_BLOCK_ROWS", 1 << 21))
    configs = {
        c.strip()
        for c in os.environ.get("BENCH_CONFIGS", "1,2,3,4,5").split(",")
        if c.strip()
    }
    unknown = configs - {"1", "2", "3", "4", "5"}
    if unknown:
        raise SystemExit(f"BENCH_CONFIGS has unknown entries: {unknown}")

    import jax
    from jax.sharding import Mesh

    from pixie_tpu.engine import Carnot
    from pixie_tpu.parallel import MeshExecutor
    from pixie_tpu.types import DataType, Relation, SemanticType

    F, I, S, T = (
        DataType.FLOAT64,
        DataType.INT64,
        DataType.STRING,
        DataType.TIME64NS,
    )

    devices = jax.devices()
    n_chips = len(devices)
    mesh = Mesh(np.array(devices), ("d",))
    carnot = Carnot(
        device_executor=MeshExecutor(mesh=mesh, block_rows=block_rows)
    )
    rng = np.random.default_rng(42)
    services = np.array(
        [f"ns/svc-{i}" for i in range(n_services)], dtype=object
    )
    detail: list[dict] = []
    headline: dict = {}

    # ---- shared large http_events table (configs 2 and 5) -----------------
    rel = Relation.of(
        ("time_", T, SemanticType.ST_TIME_NS),
        ("service", S, SemanticType.ST_SERVICE_NAME),
        ("resp_status", I),
        ("latency", F, SemanticType.ST_DURATION_NS),
    )
    true_count = np.zeros(n_services, np.int64)
    true_errors = np.zeros(n_services, np.int64)
    true_hist = np.zeros((n_services, TRUTH_BINS), np.int64)
    if configs & {"2", "5"}:
        table = carnot.table_store.create_table(
            "http_events", rel, size_limit=1 << 42
        )
        chunk = 8_000_000
        t_gen = time.perf_counter()
        for off in range(0, n_rows, chunk):
            m = min(chunk, n_rows - off)
            svc_idx = rng.integers(0, n_services, m)
            status = rng.choice(
                [200, 301, 404, 500], m, p=[0.85, 0.05, 0.05, 0.05]
            )
            latency = rng.exponential(3e7, m)
            table.write_pydict(
                {
                    "time_": np.arange(off, off + m) * 1000,
                    "service": services[svc_idx],
                    "resp_status": status,
                    "latency": latency,
                }
            )
            if "2" in configs:  # truth only feeds config 2's verify
                true_count += np.bincount(svc_idx, minlength=n_services)
                true_errors += np.bincount(
                    svc_idx, weights=(status >= 400), minlength=n_services
                ).astype(np.int64)
                bins = np.digitize(latency, TRUTH_EDGES)
                true_hist += np.bincount(
                    svc_idx * TRUTH_BINS + bins,
                    minlength=n_services * TRUTH_BINS,
                ).reshape(n_services, TRUTH_BINS)
            log(f"http_events: generated {off + m}/{n_rows} rows")
        table.compact()
        table.stop()
        log(f"http_events built in {time.perf_counter() - t_gen:.1f}s")

    # ---- config 2: service_stats (headline) -------------------------------
    if "2" in configs:
        query = (
            "df = px.DataFrame(table='http_events')\n"
            "df.failure = df.resp_status >= 400\n"
            "stats = df.groupby(['service']).agg(\n"
            "    throughput=('time_', px.count),\n"
            "    error_rate=('failure', px.mean),\n"
            "    latency=('latency', px.quantiles),\n"
            ")\n"
            "px.display(stats, 'service_stats')\n"
        )

        def verify(result) -> None:
            rows = result.table("service_stats")
            by_svc = {s: i for i, s in enumerate(rows["service"])}
            assert len(by_svc) == n_services, f"got {len(by_svc)} groups"
            assert sum(rows["throughput"]) == n_rows, "row count mismatch"
            for j, name in enumerate(services):
                i = by_svc[name]
                assert rows["throughput"][i] == true_count[j]
                want_er = true_errors[j] / true_count[j]
                assert abs(rows["error_rate"][i] - want_er) < 1e-9
                q = json.loads(rows["latency"][i])
                for key, qq in (("p50", 0.50), ("p99", 0.99)):
                    want = truth_quantile(true_hist[j], qq)
                    # sketch ~1.4% rel err + truth-bin ~0.7% -> 4% is
                    # decisive: a wrong kernel is off by far more.
                    assert abs(q[key] - want) <= 0.04 * want, (name, key)

        t0 = time.perf_counter()
        result = carnot.execute_query(query)
        cold2 = time.perf_counter() - t0
        log(f"config2 cold (compile+stage+run) {cold2:.1f}s")
        verify(result)
        best, last = best_of(lambda: carnot.execute_query(query), runs)
        verify(last)
        rps = n_rows / best / n_chips
        headline = {
            "metric": "service_stats_rows_per_sec_per_chip",
            "value": round(rps),
            "unit": "rows/s/chip",
            "vs_baseline": round(rps / 1e8, 3),
        }
        detail.append({"config": 2, "cold_s": round(cold2, 2), **headline})
        log(f"config2: {headline}")

    # ---- config 5: streaming sketches (t-digest + count-min) --------------
    if "5" in configs:
        q5 = (
            "df = px.DataFrame(table='http_events')\n"
            "s = df.groupby(['service']).agg(\n"
            "    lat=('latency', px.quantiles_tdigest),\n"
            "    freq=('resp_status', px.count_min),\n"
            ")\n"
            "px.display(s, 'sketches')\n"
        )
        t0 = time.perf_counter()
        r5 = carnot.execute_query(q5)  # cold
        cold5 = time.perf_counter() - t0
        best, last = best_of(lambda: carnot.execute_query(q5), runs)
        assert len(last.table("sketches")["service"]) == n_services
        rps = n_rows / best / n_chips
        detail.append(
            {
                "config": 5,
                "cold_s": round(cold5, 2),
                "metric": "sketch_tdigest_countmin_rows_per_sec_per_chip",
                "value": round(rps),
                "unit": "rows/s/chip",
                "vs_baseline": round(rps / 1e8, 3),
            }
        )
        log(f"config5: {detail[-1]}")

    # ---- config 1: http_data filter+project (host path) -------------------
    if "1" in configs:
        t1 = carnot.table_store.create_table("http_small", rel)
        m = n_small
        t1.write_pydict(
            {
                "time_": np.arange(m) * 1000,
                "service": services[rng.integers(0, n_services, m)],
                "resp_status": rng.choice(
                    [200, 404, 500], m, p=[0.9, 0.05, 0.05]
                ),
                "latency": rng.exponential(3e7, m),
            }
        )
        t1.compact()
        t1.stop()
        # The reference px/http_data script always bounds output with
        # head() (src/pxl_scripts/px/http_data/data.pxl); with the bound
        # the scan runs on the device (r4 scan path), which evaluates
        # predicates/projections per block and returns survivors only.
        q1 = (
            "df = px.DataFrame(table='http_small')\n"
            "df = df[df.resp_status >= 400]\n"
            "df.latency_ms = df.latency / 1000000.0\n"
            "df = df[['time_', 'service', 'latency_ms']]\n"
            "df = df.head(1000)\n"
            "px.display(df, 'out')\n"
        )
        t0 = time.perf_counter()
        carnot.execute_query(q1)  # cold
        cold1 = time.perf_counter() - t0
        best, last = best_of(lambda: carnot.execute_query(q1), runs)
        assert len(last.table("out")["time_"]) > 0
        detail.append(
            {
                "config": 1,
                "cold_s": round(cold1, 2),
                "metric": "http_data_filter_head_rows_per_sec_per_chip",
                "value": round(m / best / n_chips),
                "unit": "rows/s/chip",
            }
        )
        log(f"config1: {detail[-1]}")

    # ---- config 3: net_flow groupby(src,dst) sum + HLL distinct -----------
    if "3" in configs:
        conn_rel = Relation.of(
            ("time_", T, SemanticType.ST_TIME_NS),
            ("src", S),
            ("dst", S),
            ("remote_port", I),
            ("bytes_sent", I),
            ("bytes_recv", I),
        )
        t3 = carnot.table_store.create_table("conn_flows", conn_rel)
        m = n_small
        hosts = np.array(
            [f"default/pod-{i}" for i in range(64)], dtype=object
        )
        t3.write_pydict(
            {
                "time_": np.arange(m) * 1000,
                "src": hosts[rng.integers(0, 64, m)],
                "dst": hosts[rng.integers(0, 64, m)],
                "remote_port": rng.integers(1024, 65535, m),
                "bytes_sent": rng.integers(0, 1 << 20, m),
                "bytes_recv": rng.integers(0, 1 << 20, m),
            }
        )
        t3.compact()
        t3.stop()
        q3 = (
            "df = px.DataFrame(table='conn_flows')\n"
            "s = df.groupby(['src', 'dst']).agg(\n"
            "    bytes_sent=('bytes_sent', px.sum),\n"
            "    bytes_recv=('bytes_recv', px.sum),\n"
            "    ports=('remote_port', px.approx_count_distinct),\n"
            ")\n"
            "px.display(s, 'flows')\n"
        )
        t0 = time.perf_counter()
        carnot.execute_query(q3)  # cold
        cold3 = time.perf_counter() - t0
        best, last = best_of(lambda: carnot.execute_query(q3), runs)
        assert sum(last.table("flows")["bytes_sent"]) > 0
        detail.append(
            {
                "config": 3,
                "cold_s": round(cold3, 2),
                "metric": "net_flow_group_hll_rows_per_sec_per_chip",
                "value": round(m / best / n_chips),
                "unit": "rows/s/chip",
            }
        )
        log(f"config3: {detail[-1]}")

    # ---- config 4: flamegraph stack merge ---------------------------------
    if "4" in configs:
        st_rel = Relation.of(
            ("time_", T, SemanticType.ST_TIME_NS),
            ("stack_trace_id", I),
            ("stack_trace", S),
            ("count", I),
        )
        t4 = carnot.table_store.create_table("stacks", st_rel)
        m = n_small
        n_stacks = 4096
        stack_strs = np.array(
            [f"main;f{i % 61};g{i % 127};h{i}" for i in range(n_stacks)],
            dtype=object,
        )
        sid = rng.integers(0, n_stacks, m)
        t4.write_pydict(
            {
                "time_": np.arange(m) * 1000,
                "stack_trace_id": sid,
                "stack_trace": stack_strs[sid],
                "count": rng.integers(1, 100, m),
            }
        )
        t4.compact()
        t4.stop()
        q4 = (
            "df = px.DataFrame(table='stacks')\n"
            "s = df.groupby(['stack_trace_id']).agg(\n"
            "    stack_trace=('stack_trace', px.any),\n"
            "    count=('count', px.sum),\n"
            ")\n"
            "px.display(s, 'merged')\n"
        )
        t0 = time.perf_counter()
        carnot.execute_query(q4)  # cold
        cold4 = time.perf_counter() - t0
        best, last = best_of(lambda: carnot.execute_query(q4), runs)
        assert len(last.table("merged")["stack_trace_id"]) == n_stacks
        detail.append(
            {
                "config": 4,
                "cold_s": round(cold4, 2),
                "metric": "flamegraph_stack_merge_rows_per_sec_per_chip",
                "value": round(m / best / n_chips),
                "unit": "rows/s/chip",
            }
        )
        log(f"config4: {detail[-1]}")

    ledger_path = os.path.join(
        os.path.dirname(__file__) or ".", "BENCH_DETAIL.json"
    )
    best_prior = load_prior_best(ledger_path)
    gate_prior = best_prior
    if os.environ.get("BENCH_GATE_SELFTEST"):
        # Prove the gate trips: pretend every metric was 100x better —
        # but NEVER persist the fabricated bests (that would brick the
        # gate baseline for every later real run).
        gate_prior = {e["metric"]: e["value"] * 100 for e in detail}
    gate = apply_gate(detail, gate_prior)
    best_now = dict(best_prior)
    for e in detail:
        best_now[e["metric"]] = max(best_now.get(e["metric"], 0), e["value"])
    with open(ledger_path, "w") as f:
        json.dump(
            {"configs": detail, "best": best_now, "gate": gate}, f, indent=1
        )
    if gate["status"] == "red":
        for r in gate["regressions"]:
            log(f"PERF GATE RED: {r}")
    if not headline and detail:
        headline = {
            k: v for k, v in detail[0].items() if k not in ("config", "cold_s")
        }
    headline["gate"] = gate["status"]
    print(json.dumps(headline))


if __name__ == "__main__":
    sys.exit(main())
