"""Benchmark: px/service_stats-class query throughput on TPU.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Metric: rows/sec/chip for the BASELINE config-2 query (groupby(service) ->
count + error-rate mean + latency quantile sketch) executed by the device
pipeline (pixie_tpu.parallel) over a synthetic http_events table staged in
HBM. Baseline target (BASELINE.md): 1e8 rows/sec/chip.

Steady-state protocol: the table is staged to the device once (the HBM cold
tier) and the query runs repeatedly; we report the best of N timed runs —
matching the reference's operator-benchmark methodology (table resident in
memory, query-time work measured).
"""

import json
import os
import sys
import time

import numpy as np


def main() -> None:
    n_rows = int(os.environ.get("BENCH_ROWS", 64_000_000))
    n_services = int(os.environ.get("BENCH_SERVICES", 16))
    runs = int(os.environ.get("BENCH_RUNS", 5))

    import jax
    from jax.sharding import Mesh

    from pixie_tpu.engine import Carnot
    from pixie_tpu.parallel import MeshExecutor
    from pixie_tpu.types import DataType, Relation, SemanticType

    F, I, S, T = (
        DataType.FLOAT64,
        DataType.INT64,
        DataType.STRING,
        DataType.TIME64NS,
    )

    devices = jax.devices()
    n_chips = len(devices)
    mesh = Mesh(np.array(devices), ("d",))
    carnot = Carnot(
        device_executor=MeshExecutor(mesh=mesh, block_rows=1 << 21)
    )
    rel = Relation.of(
        ("time_", T, SemanticType.ST_TIME_NS),
        ("service", S, SemanticType.ST_SERVICE_NAME),
        ("resp_status", I),
        ("latency", F, SemanticType.ST_DURATION_NS),
    )
    table = carnot.table_store.create_table(
        "http_events", rel, size_limit=1 << 42
    )
    rng = np.random.default_rng(42)
    services = np.array(
        [f"ns/svc-{i}" for i in range(n_services)], dtype=object
    )
    chunk = 8_000_000
    for off in range(0, n_rows, chunk):
        m = min(chunk, n_rows - off)
        table.write_pydict(
            {
                "time_": np.arange(off, off + m) * 1000,
                "service": services[rng.integers(0, n_services, m)],
                "resp_status": rng.choice(
                    [200, 301, 404, 500], m, p=[0.85, 0.05, 0.05, 0.05]
                ),
                "latency": rng.exponential(3e7, m),
            }
        )
    table.compact()
    table.stop()

    query = (
        "df = px.DataFrame(table='http_events')\n"
        "df.failure = df.resp_status >= 400\n"
        "stats = df.groupby(['service']).agg(\n"
        "    throughput=('time_', px.count),\n"
        "    error_rate=('failure', px.mean),\n"
        "    latency=('latency', px.quantiles),\n"
        ")\n"
        "px.display(stats, 'service_stats')\n"
    )

    # Warm-up: compile + stage (excluded, like the reference's benchmark
    # harness excludes table build).
    result = carnot.execute_query(query)
    rows = result.table("service_stats")
    assert sum(rows["throughput"]) == n_rows, "row count mismatch"

    best = float("inf")
    for _ in range(runs):
        t0 = time.perf_counter()
        result = carnot.execute_query(query)
        best = min(best, time.perf_counter() - t0)
    rows = result.table("service_stats")
    assert sum(rows["throughput"]) == n_rows

    rows_per_sec_per_chip = n_rows / best / n_chips
    baseline = 1e8  # BASELINE.md: >1e8 rows/sec/chip target
    print(
        json.dumps(
            {
                "metric": "service_stats_rows_per_sec_per_chip",
                "value": round(rows_per_sec_per_chip),
                "unit": "rows/s/chip",
                "vs_baseline": round(rows_per_sec_per_chip / baseline, 3),
            }
        )
    )


if __name__ == "__main__":
    sys.exit(main())
