"""Benchmarks for the five BASELINE configs (+ the host-path config 0).

Prints ONE JSON line on stdout (the headline metric: config-2
px/service_stats-class throughput on TPU, target 1e8 rows/s/chip per
BASELINE.md) — emitted IMMEDIATELY after config 2 completes so a driver
timeout later in the run cannot lose it — and writes every config's
numbers to BENCH_DETAIL.json incrementally as each config finishes.

  2. service_stats — groupby(service) count + error-rate + quantile
     sketch on the device pipeline (the headline; truth-checked). Runs
     FIRST; its JSON line goes to stdout the moment it verifies.
  5. streaming sketches — t-digest + count-min over http_events latency.
  4. perf_flamegraph — stack groupby + count merge over stack_traces.
  1. http_data — filter+project+head over http_events (device scan).
  0. http_data host path — the same filter+project WITHOUT head(),
     pinned to the host engine: keeps the r3 host metric measured so the
     regression gate retains host-path coverage (VERDICT r4 weakness 5).
  3. net_flow_graph — groupby(src,dst) sum + HLL distinct. Runs LAST:
     costliest cold path, so a driver timeout costs the least.

Steady-state protocol: tables are staged once (warm-up excluded); best of
N timed runs — the reference's operator-benchmark methodology
(/root/reference/src/carnot/exec/blocking_agg_benchmark.cc). Config 2
output correctness is asserted against HOST-computed truth accumulated
during generation (exact counts/error rates; quantiles vs an independent
numpy log-histogram), so a kernel bug that preserved row counts still
fails. Cold (first-query) latency is reported per config alongside the
warm number, WITH a phase breakdown (read/plan/pack/transfer/program)
from pixie_tpu.parallel.staging.COLD_PROFILE.

Generated datasets are cached on disk (BENCH_CACHE_DIR, default
.bench_cache/) keyed by (rows, services, seed, schema version) and
reloaded in ~seconds; the JAX persistent compilation cache (.jax_cache/)
makes repeat cold queries skip XLA compiles. Both caches cut the official
driver run from tens of minutes to a few (VERDICT r4 weakness 1).

Regression gate: BENCH_DETAIL.json keeps each config's best-ever value;
any config regressing >10% vs its best marks the gate red so
non-headline regressions cannot ship silently. BENCH_GATE_SELFTEST=1
injects an impossible prior to prove the gate trips (on a deep copy —
the ledger never records fabricated baselines, ADVICE r4).

Cold staging streams by default (r6): the agg configs' first query runs
the double-buffered window pipeline (pack ∥ transfer ∥ fold; flag
``streaming_stage``, env PIXIE_TPU_STREAMING_STAGE=0 to disable,
PIXIE_TPU_STREAMING_WINDOW_ROWS to size windows), so cold breakdowns gain
the stream occupancy keys (stage_overlap, stream_windows,
stage_stream_pack/put/dispatch/drain/...; see
tools/microbench_stage_overlap.py). Warm runs are unaffected — the
streamed windows concatenate into the same HBM staged-cache entry the
monolithic path would have produced.

The compile wall (r7): cold breakdowns carry `stage_compile` (XLA
compile seconds spent on the background AOT thread, CONCURRENT with
pack/transfer), `compile_cache_hit` (persistent-cache deserializations
seen during those compiles), and `stage_compile_wait` (the
non-overlapped compile remainder the first fold blocked on). Set
BENCH_CLEAR_JAX_CACHE=1 to wipe .jax_cache/ first so those numbers
measure a REAL compile. Program signatures are bucketed
(PIXIE_TPU_SIGNATURE_BUCKETS=0 to disable) and programs are decomposed
into fold/merge/finalize units (PIXIE_TPU_PROGRAM_DECOMPOSE=0,
PIXIE_TPU_AOT_COMPILE=0 for the r6 behavior).

The sort–compact lane (r8): every config's ledger entry carries
``rows_per_sec`` (total, next to the per-chip metric the gate tracks)
and ``reduction_lanes`` — the trace-time lane choices its compiled
programs made (ops/segment.LANE_COUNTS: hll_sorted_compact vs
hll_scatter, minmax_sorted_compact vs minmax_scatter, countmin_*), so a
lane-selection regression is visible in BENCH_DETAIL.json even when the
throughput delta alone would hide inside gate tolerance. The lane is
flag-gated (PIXIE_TPU_SORTED_COMPACT=0 for the r5 scatter behavior) and
logged next to the streaming/compile knobs at startup.

Robustness knobs (r9): the fault-injection registry is OFF in benchmarks
(``PIXIE_TPU_FAULT_INJECT`` empty; tools/microbench_fault_overhead.py
holds the disabled sites to <1% on the warm path and the transport
round-trip, recorded under BENCH_DETAIL.json's ``fault_overhead`` key).
Per-query deadlines (``PIXIE_TPU_QUERY_DEADLINE_S``, 0 = off) and
partial-result degradation (``PIXIE_TPU_PARTIAL_RESULTS``) only affect
the broker path, not this single-engine driver. The device circuit
breaker (``PIXIE_TPU_DEVICE_BREAKER_THRESHOLD``, default 3 consecutive
failures; ``PIXIE_TPU_DEVICE_BREAKER_COOLDOWN_S``, default 30) trips a
repeatedly-failing program key to the host engine — a tripped breaker
during a bench run shows up as device_offload_fallback_breaker_*
metric increments and a collapsed rows/s, never as silent wrong data.
Agent reconnect backoff (``PIXIE_TPU_AGENT_BACKOFF_INITIAL_S`` /
``_MAX_S`` / ``_JITTER``) is transport-layer only.

Serving (r12): config 6 (opt-in, BENCH_CONFIGS=...,6) runs the
tools/soak_serving.py concurrency harness — an in-process broker
cluster serving BENCH_SOAK_CLIENTS (64) concurrent scripted clients
with admission control, per-tenant weighted fair queueing, shared
scans, and an HBM residency budget — and records queries/s, p50/p99
latency, shared-scan dispatch reduction, evictions, and rejections in
BENCH_DETAIL.json. The single-engine configs run with serving OFF
(``PIXIE_TPU_SERVING_ENABLED``/``PIXIE_TPU_SHARED_SCANS``/
``PIXIE_TPU_HBM_BUDGET_MB`` are logged at startup); shared scans only
change behavior under concurrency, and the residency pool with no
byte budget reproduces the old entry-count LRU exactly.

Fleet placement (r18): config 7 (opt-in, BENCH_CONFIGS=...,7) runs
the fleet workload twice — a 1-agent thrash baseline, then
BENCH_FLEET_AGENTS (4) placement-routed agents — and records
placement hit-rate, per-agent balance, and the aggregate device-
capacity QPS scaling into BENCH_DETAIL.json's ``fleet`` block
(capacity, not wall-clock: in-process chips share one host core, so
scaling is measured per-chip like the rows/s/chip configs).

The join lane (r19): config 8 (opt-in, BENCH_CONFIGS=...,8) runs a
representative dim×fact equijoin (svc_owners × join_fact on service)
and records ``join_lane`` ("device" when the program cache traced the
sort-merge lane — ops/segment.LANE_COUNTS key ``join_sort_merge`` —
"host" when any gate declined) and ``join_rows_per_sec`` next to the
per-chip metric, both ALWAYS present so a lane-selection regression is
visible even inside gate tolerance. Output correctness is asserted
in-run (both key columns of every emitted pair are equal, row count
matches the host-computed expectation). Knobs: ``device_join`` /
``device_join_min_rows`` / ``device_join_max_out`` are logged at
startup; BENCH_JOIN_ROWS sizes the fact side (default 4M — inside the
default device_join_max_out so the lane engages at stock flags).

Materialized views (r20): config 9 (opt-in, BENCH_CONFIGS=...,9) runs
the dashboard-repeat soak workload with the view plane ON — the panel
scripts are registered as materialized views, clients re-run them, and
reads merge persisted partial-agg state with a tail delta fold instead
of folding from scratch. Asserts hit rate >= 0.9 and fold-dispatch
reduction >= 5x vs the views-off one-fold-per-request cost, with the
in-run bit-identity verify as the correctness gate; the full block
lands in BENCH_DETAIL.json's ``views`` key.

Mesh execution (r21): config 10 (opt-in, BENCH_CONFIGS=...,10) sweeps
the fold over mesh widths (hosts:1/2/4/8 re-partitioning the same
device pool) through tools/microbench_mesh.py: bit-identity at every
width is the correctness gate, the always-present ``mesh_scaling_x``
headline (per-device fold rate at width 4 vs 1-host) must stay >= 0.7,
and the sweep lands in BENCH_DETAIL.json's ``mesh`` key.

Cost model (r22): config 11 (opt-in, BENCH_CONFIGS=...,11) measures the
learned CostModel's prediction accuracy over real engine dispatches
through tools/microbench_cost_model.py: the warmed pooled p50 relative
error (predict-before-ingest vs measured wall) must stay <= 0.30, the
headline ``cost_model_warmed_p50_accuracy_x`` is its inverse, and the
sweep lands in BENCH_DETAIL.json's ``cost_model`` key.

Mesh chaos recovery (r23): config 12 (opt-in, BENCH_CONFIGS=...,12)
kills one simulated host mid-stream during a windowed fold at
hosts:2,d:N/2 (tools/microbench_mesh.py MB_MESH_CHAOS path): the
degraded-geometry ladder must recover bit-identically from the last
window checkpoint, the headline ``mesh_chaos_checkpoint_saved_fraction``
is the stream fraction NOT refolded, and recovery seconds + the
refolded-window fraction land in BENCH_DETAIL.json's ``mesh_chaos`` key.

Ingest chaos soak (r24): config 13 (opt-in, BENCH_CONFIGS=...,13) runs
tools/soak_ingest.py's mixed-protocol replay (all six parsers) through
the bounded-tracker/shedding-ladder/quarantine ingest plane with the
ingest.* fault sites armed and concurrent queries checked
bit-identical; asserts the exact drop-accounting invariant, records
offered events/s (headline ``ingest_soak_events_per_s``) plus drop
fractions by reason into BENCH_DETAIL.json's ``ingest_soak`` key.

Env knobs: BENCH_ROWS (configs 2/5; default 256M), BENCH_SMALL_ROWS
(configs 1/3/4; default 64M), BENCH_HOST_ROWS (config 0; default 8M),
BENCH_RUNS, BENCH_SERVICES, BENCH_CONFIGS (comma list, default
"2,5,4,1,0,3" — also the execution order; add 6 for the serving soak),
BENCH_BLOCK_ROWS, BENCH_CACHE_DIR, BENCH_NO_DATA_CACHE=1 to force
regeneration, BENCH_CLEAR_JAX_CACHE=1 to clear the persistent compile
cache, BENCH_SOAK_CLIENTS/BENCH_SOAK_REQUESTS/BENCH_SOAK_ROWS for
config 6, BENCH_FLEET_AGENTS/BENCH_FLEET_CLIENTS/BENCH_FLEET_ROWS/
BENCH_FLEET_TABLES/BENCH_FLEET_HBM_MB for config 7, BENCH_JOIN_ROWS
for config 8, BENCH_VIEWS_CLIENTS/BENCH_VIEWS_REQUESTS/
BENCH_VIEWS_ROWS for config 9, BENCH_CM_ROWS for config 11,
BENCH_MESH_ROWS/BENCH_MESH_WINDOWS for config 12,
BENCH_INGEST_SECONDS/BENCH_INGEST_FEEDERS/BENCH_INGEST_CLIENTS for
config 13.
"""

import copy
import json
import math
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.abspath(__file__))


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


GATE_TOLERANCE = 0.10  # >10% below best-ever trips the gate
_SCHEMA_V = "v1"  # bump to invalidate cached datasets


def load_prior_best(path: str) -> dict:
    """metric name -> best-ever value from the ledger (accepts the old
    list format and the current dict format)."""
    try:
        with open(path) as f:
            prior = json.load(f)
    except (OSError, json.JSONDecodeError):
        return {}
    if isinstance(prior, list):  # r3 format
        return {
            e["metric"]: e["value"]
            for e in prior
            if "metric" in e and "value" in e
        }
    best = dict(prior.get("best", {}))
    for e in prior.get("configs", []):
        if "metric" in e and "value" in e:
            best[e["metric"]] = max(best.get(e["metric"], 0), e["value"])
    return best


def apply_gate(detail: list[dict], best: dict) -> dict:
    """Mark regressions >10% vs best-ever; returns the gate summary."""
    regressions = []
    for e in detail:
        prior = best.get(e["metric"])
        if prior and e["value"] < prior * (1 - GATE_TOLERANCE):
            e["regressed_vs_best"] = prior
            regressions.append(
                f"{e['metric']}: {e['value']:.3g} < best {prior:.3g}"
            )
    return {
        "status": "red" if regressions else "green",
        "regressions": regressions,
    }


# Host-truth latency histogram: log-spaced bins, ~0.7% relative bin width —
# an independent numpy implementation (np.digitize), NOT pixie_tpu's
# histogram op, so it cross-checks the device sketch rather than mirroring
# its bugs.
TRUTH_BINS = 4096
TRUTH_LO, TRUTH_HI = 1.0, 1e12
TRUTH_EDGES = np.logspace(
    math.log10(TRUTH_LO), math.log10(TRUTH_HI), TRUTH_BINS - 1
)


def truth_quantile(hist_row: np.ndarray, q: float) -> float:
    total = hist_row.sum()
    if total == 0:
        return 0.0
    cum = np.cumsum(hist_row)
    i = int(np.searchsorted(cum, q * total))
    i = min(i, TRUTH_BINS - 1)
    lo = TRUTH_EDGES[i - 1] if i >= 1 else TRUTH_LO
    hi = TRUTH_EDGES[i] if i < len(TRUTH_EDGES) else TRUTH_HI
    return math.sqrt(lo * hi)


def best_of(fn, runs: int):
    """(best wall-clock, last run's result) — so callers can verify a
    *timed* run's output instead of paying an extra execution."""
    best = float("inf")
    result = None
    for _ in range(runs):
        t0 = time.perf_counter()
        result = fn()
        best = min(best, time.perf_counter() - t0)
    return best, result


class DatasetCache:
    """Disk cache for generated benchmark datasets: one .npz per dataset,
    keyed by shape parameters + seed + schema version. Generation at
    256M rows costs minutes of RNG + encode; reload costs seconds."""

    def __init__(self):
        self.dir = os.environ.get(
            "BENCH_CACHE_DIR", os.path.join(REPO, ".bench_cache")
        )
        self.enabled = not os.environ.get("BENCH_NO_DATA_CACHE")
        if self.enabled:
            os.makedirs(self.dir, exist_ok=True)

    def get_or_build(self, key: str, build):
        """build() -> dict[str, np.ndarray]; returns the dict (from disk
        when cached)."""
        path = os.path.join(self.dir, f"{key}_{_SCHEMA_V}.npz")
        if self.enabled and os.path.exists(path):
            t0 = time.perf_counter()
            with np.load(path) as z:
                out = {k: z[k] for k in z.files}
            log(f"dataset cache hit {key} ({time.perf_counter()-t0:.1f}s)")
            return out
        t0 = time.perf_counter()
        out = build()
        log(f"dataset {key} generated in {time.perf_counter()-t0:.1f}s")
        if self.enabled:
            tmp = path + ".tmp"
            np.savez(tmp, **out)
            os.replace(tmp + ".npz" if os.path.exists(tmp + ".npz") else tmp, path)
            log(f"dataset {key} cached to {path}")
        return out


def _pick(rng, options: np.ndarray, p: list[float], m: int) -> np.ndarray:
    """Weighted choice via searchsorted — much faster than rng.choice."""
    cum = np.cumsum(p)
    return options[np.searchsorted(cum, rng.random(m), side="right")]


class Ledger:
    """Incremental BENCH_DETAIL.json writer: every finished config is
    persisted immediately so a driver timeout later cannot lose it."""

    def __init__(self):
        self.path = os.path.join(REPO, "BENCH_DETAIL.json")
        self.best_prior = load_prior_best(self.path)
        self.detail: list[dict] = []

    def add(self, entry: dict) -> None:
        self.detail.append(entry)
        log(f"config{entry['config']}: {json.dumps(entry)}")
        self.flush()

    def gate(self) -> dict:
        detail = self.detail
        gate_prior = self.best_prior
        if os.environ.get("BENCH_GATE_SELFTEST"):
            # Prove the gate trips — on a COPY: the ledger must never
            # record fabricated baselines or their regression markers.
            detail = copy.deepcopy(self.detail)
            gate_prior = {e["metric"]: e["value"] * 100 for e in detail}
        return apply_gate(detail, gate_prior)

    def flush(self) -> None:
        gate = self.gate()
        best_now = dict(self.best_prior)
        for e in self.detail:
            best_now[e["metric"]] = max(
                best_now.get(e["metric"], 0), e["value"]
            )
        # Read-modify-write: the microbench/soak recorders merge their
        # own top-level keys (mesh, ingest_soak, fault_overhead, ...)
        # into this file — a bench run must not clobber them.
        doc: dict = {}
        try:
            with open(self.path) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            doc = {}
        doc.update({"configs": self.detail, "best": best_now, "gate": gate})
        with open(self.path, "w") as f:
            json.dump(doc, f, indent=1)


def main() -> None:
    n_rows = int(os.environ.get("BENCH_ROWS", 256_000_000))
    n_small = int(os.environ.get("BENCH_SMALL_ROWS", 64_000_000))
    n_host = int(os.environ.get("BENCH_HOST_ROWS", 8_000_000))
    n_services = int(os.environ.get("BENCH_SERVICES", 16))
    runs = int(os.environ.get("BENCH_RUNS", 5))
    block_rows = int(os.environ.get("BENCH_BLOCK_ROWS", 1 << 21))
    order = [
        c.strip()
        for c in os.environ.get("BENCH_CONFIGS", "2,5,4,1,0,3").split(",")
        if c.strip()
    ]
    unknown = set(order) - {
        "0", "1", "2", "3", "4", "5", "6", "7", "8", "9", "10", "11",
        "12", "13",
    }
    if unknown:
        raise SystemExit(f"BENCH_CONFIGS has unknown entries: {unknown}")
    configs = set(order)

    import jax

    # Persistent XLA compilation cache: repeat cold queries (including the
    # driver's official run after this round's pre-warm) skip compiles.
    # BENCH_CLEAR_JAX_CACHE=1 wipes it first so cold-compile numbers are
    # honest (stage_compile measures a REAL compile, not a deserialize)
    # and compile regressions gate instead of hiding behind a warm cache.
    cache_dir = os.environ.get(
        "JAX_COMPILATION_CACHE_DIR", os.path.join(REPO, ".jax_cache")
    )
    if os.environ.get("BENCH_CLEAR_JAX_CACHE"):
        import shutil

        shutil.rmtree(cache_dir, ignore_errors=True)
        log(f"cleared persistent compilation cache {cache_dir}")
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    from jax.sharding import Mesh

    from pixie_tpu.engine import Carnot
    from pixie_tpu.parallel import MeshExecutor
    from pixie_tpu.parallel.staging import reset_cold_profile
    from pixie_tpu.table.column import DictColumn
    from pixie_tpu.types import DataType, Relation, SemanticType

    F, I, S, T = (
        DataType.FLOAT64,
        DataType.INT64,
        DataType.STRING,
        DataType.TIME64NS,
    )

    from pixie_tpu.utils import flags

    # Imported for its flag DEFINITION (self_telemetry_interval_s): the
    # startup log below reads it, and Carnot only imports the module
    # lazily after that line.
    import pixie_tpu.ingest.self_telemetry  # noqa: F401
    from pixie_tpu.ops import segment as segment_ops

    devices = jax.devices()
    n_chips = len(devices)
    mesh = Mesh(np.array(devices), ("d",))
    log(
        f"streaming_stage={flags.streaming_stage} "
        f"window_rows={flags.streaming_window_rows} "
        f"sorted_compact={flags.sorted_compact} "
        f"sorted_min_rows={segment_ops.SORTED_MIN_ROWS} "
        f"prewarm_compile={flags.prewarm_compile} "
        f"fault_inject={flags.fault_inject or 'off'} "
        f"device_breaker={flags.device_breaker_threshold}"
        f"@{flags.device_breaker_cooldown_s}s "
        f"query_tracing={flags.query_tracing} "
        f"self_telemetry_interval_s={flags.self_telemetry_interval_s} "
        # Serving knobs (r12): this single-engine driver runs with
        # serving OFF by default; config 6 (BENCH_CONFIGS=6) pins its
        # own serving flags for the concurrency soak and restores them.
        f"serving_enabled={flags.serving_enabled} "
        f"hbm_budget_mb={flags.hbm_budget_mb} "
        f"shared_scans={flags.shared_scans}"
        f"@{flags.shared_scan_window_ms}ms "
        # r16: predicate-batched shared scans + closed-loop admission.
        f"pred_batching={flags.shared_scan_predicate_batching}"
        f"<={flags.shared_scan_max_batch} "
        f"admission={flags.admission_max_concurrent}"
        f"/{flags.admission_max_queue}q "
        f"admission_controller={flags.admission_controller} "
        # r13 knobs: the staging codec (wire compression + device
        # decode) and device-resident incremental ingest (BENCH_RESIDENT
        # enables rings for the http_small table before its build).
        f"staging_codec={flags.staging_codec}"
        f"@{flags.staging_codec_min_ratio} "
        f"resident_ingest={flags.resident_ingest} "
        f"resident_window_rows={flags.resident_window_rows} "
        f"resident_max_windows={flags.resident_max_windows} "
        # r15 knobs: query-attributed profiling (thread attribution +
        # device dispatch/program records + HBM usage snapshots).
        f"resource_attribution={flags.resource_attribution} "
        f"hbm_snapshot_interval_s={flags.hbm_snapshot_interval_s} "
        # r17 knobs: transparent fragment failover (broker-plane;
        # this single-engine driver never exercises them, the chaos
        # soak and tests/test_failover.py do).
        f"fragment_failover={flags.fragment_failover}"
        f"x{flags.fragment_max_retries} "
        f"hedged={flags.hedged_requests}"
        f"@q{flags.hedge_quantile} "
        f"ring_replication={flags.ring_replication_factor} "
        # r19 knobs: the device sort-merge join lane (config 8; joins in
        # any config's queries take it when the gates admit the shape).
        f"device_join={flags.device_join}"
        f">={flags.device_join_min_rows}rows"
        f"<={flags.device_join_max_out}out"
    )
    carnot = Carnot(
        device_executor=MeshExecutor(mesh=mesh, block_rows=block_rows)
    )
    cache = DatasetCache()
    ledger = Ledger()
    services = np.array(
        [f"ns/svc-{i}" for i in range(n_services)], dtype=object
    )
    headline_printed = False

    def breakdown() -> dict:
        snap = reset_cold_profile()
        # Always-present compile keys (r7): stage_compile is the XLA
        # compile seconds spent CONCURRENTLY with pack/transfer on the
        # AOT thread; compile_cache_hit counts persistent .jax_cache
        # deserializations observed during those compiles (honest only
        # when BENCH_CLEAR_JAX_CACHE=1 cleared the cache first);
        # stage_compile_wait is the non-overlapped remainder the first
        # fold dispatch actually blocked on.
        snap.setdefault("stage_compile", 0.0)
        snap.setdefault("compile_cache_hit", 0.0)
        # r16: decode-program compiles carry their own key so
        # stage_compile stays the FOLD compile signal.
        snap.setdefault("decode_compile", 0.0)
        # r8 keys: warm_compile is the background AOT of the
        # warm/monolithic fold (concurrent with the cold query's tail);
        # prewarm_hit counts query folds served by a table-create
        # prewarm (flag prewarm_compile).
        snap.setdefault("warm_compile", 0.0)
        snap.setdefault("prewarm_hit", 0.0)
        # r13 keys: the staging codec + resident-ingest breakdown.
        # wire_bytes is what the host→HBM tunnel actually carried;
        # stage_bytes is what landed (decoded blocks); codec_ratio is
        # their quotient — the 'kill the transfer floor' headline.
        # stage_encode/stage_decode are the host encode and device
        # decode seconds; stage_resident_hits counts stream windows
        # served from HBM ring windows (zero wire bytes).
        snap.setdefault("stage_encode", 0.0)
        snap.setdefault("stage_decode", 0.0)
        snap.setdefault("stage_bytes", 0.0)
        snap.setdefault("wire_bytes", 0.0)
        snap.setdefault("stage_resident_hits", 0.0)
        snap["codec_ratio"] = (
            round(snap["stage_bytes"] / snap["wire_bytes"], 2)
            if snap["wire_bytes"]
            else 0.0
        )
        # r9 keys (cumulative this process): circuit-breaker activity on
        # the device offload lane — nonzero means some queries ran on the
        # host engine behind an open breaker, which explains a collapsed
        # rows/s without silent wrong data.
        from pixie_tpu.utils import metrics_registry as _mr

        snap["breaker_trips"] = _mr().counter(
            "device_offload_fallback_breaker_trips_total"
        ).value()
        snap["breaker_open_skips"] = _mr().counter(
            "device_offload_fallback_breaker_open_total"
        ).value()
        return {k: round(v, 2) for k, v in sorted(snap.items())}

    def create_table_no_ring(name, tbl_rel, **kw):
        # Tables that should NOT get an HBM resident-ingest ring even
        # when BENCH_RESIDENT turned the flag on for http_small: rings
        # hold RAW-dtype blocks, and giving every bench table one would
        # crowd HBM that the staged-cache entries need.
        was = flags.resident_ingest
        flags.set("resident_ingest", False)
        try:
            return carnot.table_store.create_table(name, tbl_rel, **kw)
        finally:
            flags.set("resident_ingest", was)

    def cold_run(query):
        reset_cold_profile()
        # Reduction-lane telemetry is trace-time: reset here so each
        # config's ledger entry records the lanes ITS programs chose
        # (sort–compact vs scatter vs matmul; ops/segment.LANE_COUNTS).
        segment_ops.reduce_lanes(reset=True)
        t0 = time.perf_counter()
        result = carnot.execute_query(query)
        cold_s = time.perf_counter() - t0
        return result, round(cold_s, 2), breakdown()

    # ---- shared large http_events table (configs 2 and 5) -----------------
    rel = Relation.of(
        ("time_", T, SemanticType.ST_TIME_NS),
        ("service", S, SemanticType.ST_SERVICE_NAME),
        ("resp_status", I),
        ("latency", F, SemanticType.ST_DURATION_NS),
    )
    true_count = true_errors = true_hist = None
    _built = set()

    def ensure_http_table():
        nonlocal true_count, true_errors, true_hist
        if "http" in _built:
            return
        _built.add("http")

        def build_http():
            rng = np.random.default_rng(42)
            svc_idx = np.empty(n_rows, np.uint8)
            status = np.empty(n_rows, np.uint16)
            latency = np.empty(n_rows, np.float64)
            tc = np.zeros(n_services, np.int64)
            te = np.zeros(n_services, np.int64)
            th = np.zeros((n_services, TRUTH_BINS), np.int64)
            chunk = 16_000_000
            opts = np.array([200, 301, 404, 500], np.uint16)
            for off in range(0, n_rows, chunk):
                m = min(chunk, n_rows - off)
                si = rng.integers(0, n_services, m, dtype=np.uint8)
                st = _pick(rng, opts, [0.85, 0.05, 0.05, 0.05], m)
                la = rng.exponential(3e7, m)
                svc_idx[off : off + m] = si
                status[off : off + m] = st
                latency[off : off + m] = la
                tc += np.bincount(si, minlength=n_services)
                te += np.bincount(
                    si, weights=(st >= 400), minlength=n_services
                ).astype(np.int64)
                bins = np.digitize(la, TRUTH_EDGES)
                th += np.bincount(
                    si.astype(np.int64) * TRUTH_BINS + bins,
                    minlength=n_services * TRUTH_BINS,
                ).reshape(n_services, TRUTH_BINS)
                log(f"http_events: generated {off + m}/{n_rows} rows")
            return {
                "svc_idx": svc_idx,
                "status": status,
                "latency": latency,
                "true_count": tc,
                "true_errors": te,
                "true_hist": th,
            }

        d = cache.get_or_build(f"http_{n_rows}_{n_services}_s42", build_http)
        true_count = d["true_count"]
        true_errors = d["true_errors"]
        true_hist = d["true_hist"]
        t_gen = time.perf_counter()
        table = create_table_no_ring(
            "http_events", rel, size_limit=1 << 42
        )
        svc_dict = table.dictionaries["service"]
        for name in services:  # identity codes 0..n-1 (encode() would
            svc_dict.get_code(name)  # assign codes in SORTED order)
        chunk = 16_000_000
        for off in range(0, n_rows, chunk):
            m = min(chunk, n_rows - off)
            table.write_pydict(
                {
                    "time_": np.arange(off, off + m, dtype=np.int64) * 1000,
                    "service": DictColumn(
                        d["svc_idx"][off : off + m].astype(np.int32),
                        svc_dict,
                    ),
                    "resp_status": d["status"][off : off + m],
                    "latency": d["latency"][off : off + m],
                }
            )
        table.compact()
        table.stop()
        assert table.min_row_id() == 0 and table.end_row_id() == n_rows, (
            "table expired rows; the metric would be inflated"
        )
        log(f"http_events table built in {time.perf_counter() - t_gen:.1f}s")

    # ---- config 2: service_stats (headline) -------------------------------
    def run_config_2():
        nonlocal headline_printed
        ensure_http_table()
        query = (
            "df = px.DataFrame(table='http_events')\n"
            "df.failure = df.resp_status >= 400\n"
            "stats = df.groupby(['service']).agg(\n"
            "    throughput=('time_', px.count),\n"
            "    error_rate=('failure', px.mean),\n"
            "    latency=('latency', px.quantiles),\n"
            ")\n"
            "px.display(stats, 'service_stats')\n"
        )

        def verify(result) -> None:
            rows = result.table("service_stats")
            by_svc = {s: i for i, s in enumerate(rows["service"])}
            assert len(by_svc) == n_services, f"got {len(by_svc)} groups"
            assert sum(rows["throughput"]) == n_rows, "row count mismatch"
            for j, name in enumerate(services):
                i = by_svc[name]
                assert rows["throughput"][i] == true_count[j]
                want_er = true_errors[j] / true_count[j]
                assert abs(rows["error_rate"][i] - want_er) < 1e-9
                q = json.loads(rows["latency"][i])
                for key, qq in (("p50", 0.50), ("p99", 0.99)):
                    want = truth_quantile(true_hist[j], qq)
                    # sketch ~1.4% rel err + truth-bin ~0.7% -> 4% is
                    # decisive: a wrong kernel is off by far more.
                    assert abs(q[key] - want) <= 0.04 * want, (name, key)

        result, cold2, bd = cold_run(query)
        log(f"config2 cold (compile+stage+run) {cold2:.1f}s {bd}")
        verify(result)
        best, last = best_of(lambda: carnot.execute_query(query), runs)
        verify(last)
        rps = n_rows / best / n_chips
        headline = {
            "metric": "service_stats_rows_per_sec_per_chip",
            "value": round(rps),
            "unit": "rows/s/chip",
            "vs_baseline": round(rps / 1e8, 3),
        }
        ledger.add(
            {
                "config": 2,
                "cold_s": cold2,
                "cold_breakdown": bd,
                "rows_per_sec": round(n_rows / best),
                "reduction_lanes": segment_ops.reduce_lanes(reset=True),
                **headline,
            }
        )
        # stdout headline NOW — the driver must capture it even if a later
        # config blows its timeout. Gate reflects configs finished so far
        # vs the prior ledger; the final ledger carries the full gate.
        headline["gate"] = ledger.gate()["status"]
        print(json.dumps(headline), flush=True)
        headline_printed = True

    # ---- config 5: streaming sketches (t-digest + count-min) --------------
    def run_config_5():
        ensure_http_table()
        q5 = (
            "df = px.DataFrame(table='http_events')\n"
            "s = df.groupby(['service']).agg(\n"
            "    lat=('latency', px.quantiles_tdigest),\n"
            "    freq=('resp_status', px.count_min),\n"
            ")\n"
            "px.display(s, 'sketches')\n"
        )
        r5, cold5, bd = cold_run(q5)
        best, last = best_of(lambda: carnot.execute_query(q5), runs)
        assert len(last.table("sketches")["service"]) == n_services
        rps = n_rows / best / n_chips
        ledger.add(
            {
                "config": 5,
                "cold_s": cold5,
                "cold_breakdown": bd,
                "rows_per_sec": round(n_rows / best),
                "reduction_lanes": segment_ops.reduce_lanes(reset=True),
                "metric": "sketch_tdigest_countmin_rows_per_sec_per_chip",
                "value": round(rps),
                "unit": "rows/s/chip",
                "vs_baseline": round(rps / 1e8, 3),
            }
        )

    # ---- config 4: flamegraph stack merge ---------------------------------
    def run_config_4():
        st_rel = Relation.of(
            ("time_", T, SemanticType.ST_TIME_NS),
            ("stack_trace_id", I),
            ("stack_trace", S),
            ("count", I),
        )
        n_stacks = 4096

        def build_stacks():
            rng = np.random.default_rng(43)
            sid = rng.integers(0, n_stacks, n_small, dtype=np.uint16)
            cnt = rng.integers(1, 100, n_small, dtype=np.uint8)
            return {"sid": sid, "cnt": cnt}

        d4 = cache.get_or_build(f"stacks_{n_small}_s43", build_stacks)
        t4 = create_table_no_ring(
            "stacks", st_rel, size_limit=1 << 42
        )
        stack_dict = t4.dictionaries["stack_trace"]
        for i in range(n_stacks):  # identity codes, matching sid values
            stack_dict.get_code(f"main;f{i % 61};g{i % 127};h{i}")
        chunk = 16_000_000
        for off in range(0, n_small, chunk):
            m = min(chunk, n_small - off)
            sid = d4["sid"][off : off + m]
            t4.write_pydict(
                {
                    "time_": np.arange(off, off + m, dtype=np.int64) * 1000,
                    "stack_trace_id": sid,
                    "stack_trace": DictColumn(
                        sid.astype(np.int32), stack_dict
                    ),
                    "count": d4["cnt"][off : off + m],
                }
            )
        t4.compact()
        t4.stop()
        assert t4.min_row_id() == 0 and t4.end_row_id() == n_small, (
            "table expired rows; the metric would be inflated"
        )
        q4 = (
            "df = px.DataFrame(table='stacks')\n"
            "s = df.groupby(['stack_trace_id']).agg(\n"
            "    stack_trace=('stack_trace', px.any),\n"
            "    count=('count', px.sum),\n"
            ")\n"
            "px.display(s, 'merged')\n"
        )
        _, cold4, bd = cold_run(q4)
        best, last = best_of(lambda: carnot.execute_query(q4), runs)
        assert len(last.table("merged")["stack_trace_id"]) == n_stacks
        ledger.add(
            {
                "config": 4,
                "cold_s": cold4,
                "cold_breakdown": bd,
                "rows_per_sec": round(n_small / best),
                "reduction_lanes": segment_ops.reduce_lanes(reset=True),
                "metric": "flamegraph_stack_merge_rows_per_sec_per_chip",
                "value": round(n_small / best / n_chips),
                "unit": "rows/s/chip",
            }
        )

    # ---- configs 1 + 0 share the http_small table -------------------------
    def ensure_small_table():
        if "small" in _built:
            return
        _built.add("small")
        # r13: http_small is the resident-ingest showcase (BENCH_RESIDENT,
        # default on): the flag flips BEFORE creation so the engine's
        # create listener attaches an HBM ring, the write loop below
        # stages full windows incrementally (codec-compressed wire), and
        # config 1's cold query finds them resident — stage_transfer ≈ 0
        # for the in-window span, wire_bytes ≪ stage_bytes. The flag
        # stays on so config 1/0 queries take the resident path; other
        # bench tables use create_table_no_ring.
        if os.environ.get("BENCH_RESIDENT", "1") == "1":
            flags.set("resident_ingest", True)
        t1 = carnot.table_store.create_table(
            "http_small", rel, size_limit=1 << 42
        )
        sd = t1.dictionaries["service"]
        for name in services:
            sd.get_code(name)

        def build_small():
            rng = np.random.default_rng(44)
            return {
                "svc_idx": rng.integers(
                    0, n_services, n_small, dtype=np.uint8
                ),
                "status": _pick(
                    rng,
                    np.array([200, 404, 500], np.uint16),
                    [0.9, 0.05, 0.05],
                    n_small,
                ),
                "latency": rng.exponential(3e7, n_small),
            }

        d1 = cache.get_or_build(f"httpsmall_{n_small}_s44", build_small)
        chunk = 16_000_000
        for off in range(0, n_small, chunk):
            m = min(chunk, n_small - off)
            t1.write_pydict(
                {
                    "time_": np.arange(off, off + m, dtype=np.int64) * 1000,
                    "service": DictColumn(
                        d1["svc_idx"][off : off + m].astype(np.int32), sd
                    ),
                    "resp_status": d1["status"][off : off + m],
                    "latency": d1["latency"][off : off + m],
                }
            )
        t1.compact()
        t1.stop()
        assert t1.min_row_id() == 0 and t1.end_row_id() == n_small, (
            "table expired rows; the metric would be inflated"
        )

    def run_config_1():
        ensure_small_table()
        # The reference px/http_data script always bounds output with
        # head() (src/pxl_scripts/px/http_data/data.pxl); with the bound
        # the scan runs on the device (r4 scan path), which evaluates
        # predicates/projections per block and returns survivors only.
        q1 = (
            "df = px.DataFrame(table='http_small')\n"
            "df = df[df.resp_status >= 400]\n"
            "df.latency_ms = df.latency / 1000000.0\n"
            "df = df[['time_', 'service', 'latency_ms']]\n"
            "df = df.head(1000)\n"
            "px.display(df, 'out')\n"
        )
        _, cold1, bd = cold_run(q1)
        best, last = best_of(lambda: carnot.execute_query(q1), runs)
        assert len(last.table("out")["time_"]) > 0
        ledger.add(
            {
                "config": 1,
                "cold_s": cold1,
                "cold_breakdown": bd,
                "rows_per_sec": round(n_small / best),
                "reduction_lanes": segment_ops.reduce_lanes(reset=True),
                "metric": "http_data_filter_head_rows_per_sec_per_chip",
                "value": round(n_small / best / n_chips),
                "unit": "rows/s/chip",
            }
        )

    def run_config_0():
        ensure_small_table()
        # Host engine path: no head() bound -> the full selection is the
        # output, which stays on the host engine by design. Smaller row
        # count (default 8M): the metric tracks host-path regressions, not
        # the chip. start_time pins the window so the device scan-limit
        # cannot pick it up.
        q0 = (
            f"df = px.DataFrame(table='http_small', start_time=0, "
            f"end_time={n_host * 1000})\n"
            "df = df[df.resp_status >= 400]\n"
            "df.latency_ms = df.latency / 1000000.0\n"
            "df = df[['time_', 'service', 'latency_ms']]\n"
            "px.display(df, 'out')\n"
        )
        _, cold0, bd = cold_run(q0)
        best, last = best_of(lambda: carnot.execute_query(q0), runs)
        assert len(last.table("out")["time_"]) > 0
        ledger.add(
            {
                "config": 0,
                "cold_s": cold0,
                "cold_breakdown": bd,
                "rows_per_sec": round(n_host / best),
                "reduction_lanes": segment_ops.reduce_lanes(reset=True),
                "metric": "http_data_filter_project_rows_per_sec",
                "value": round(n_host / best),
                "unit": "rows/s",
            }
        )

    # ---- config 3: net_flow groupby(src,dst) sum + HLL distinct -----------
    def run_config_3():
        conn_rel = Relation.of(
            ("time_", T, SemanticType.ST_TIME_NS),
            ("src", S),
            ("dst", S),
            ("remote_port", I),
            ("bytes_sent", I),
            ("bytes_recv", I),
        )
        t3 = create_table_no_ring(
            "conn_flows", conn_rel, size_limit=1 << 42
        )
        hosts = np.array(
            [f"default/pod-{i}" for i in range(64)], dtype=object
        )
        for col in ("src", "dst"):
            for h in hosts:
                t3.dictionaries[col].get_code(h)

        def build_flows():
            rng = np.random.default_rng(45)
            return {
                "src": rng.integers(0, 64, n_small, dtype=np.uint8),
                "dst": rng.integers(0, 64, n_small, dtype=np.uint8),
                "port": rng.integers(1024, 65535, n_small, dtype=np.uint16),
                "bs": rng.integers(0, 1 << 20, n_small, dtype=np.uint32),
                "br": rng.integers(0, 1 << 20, n_small, dtype=np.uint32),
            }

        d3 = cache.get_or_build(f"flows_{n_small}_s45", build_flows)
        chunk = 16_000_000
        for off in range(0, n_small, chunk):
            m = min(chunk, n_small - off)
            t3.write_pydict(
                {
                    "time_": np.arange(off, off + m, dtype=np.int64) * 1000,
                    "src": DictColumn(
                        d3["src"][off : off + m].astype(np.int32),
                        t3.dictionaries["src"],
                    ),
                    "dst": DictColumn(
                        d3["dst"][off : off + m].astype(np.int32),
                        t3.dictionaries["dst"],
                    ),
                    "remote_port": d3["port"][off : off + m],
                    "bytes_sent": d3["bs"][off : off + m],
                    "bytes_recv": d3["br"][off : off + m],
                }
            )
        t3.compact()
        t3.stop()
        assert t3.min_row_id() == 0 and t3.end_row_id() == n_small, (
            "table expired rows; the metric would be inflated"
        )
        q3 = (
            "df = px.DataFrame(table='conn_flows')\n"
            "s = df.groupby(['src', 'dst']).agg(\n"
            "    bytes_sent=('bytes_sent', px.sum),\n"
            "    bytes_recv=('bytes_recv', px.sum),\n"
            "    ports=('remote_port', px.approx_count_distinct),\n"
            ")\n"
            "px.display(s, 'flows')\n"
        )
        _, cold3, bd = cold_run(q3)
        best, last = best_of(lambda: carnot.execute_query(q3), runs)
        assert sum(last.table("flows")["bytes_sent"]) > 0
        ledger.add(
            {
                "config": 3,
                "cold_s": cold3,
                "cold_breakdown": bd,
                "rows_per_sec": round(n_small / best),
                # The config the r8 sort–compact lane targets: expect
                # hll_sorted_compact here on TPU (scatter on CPU / below
                # SORTED_MIN_ROWS).
                "reduction_lanes": segment_ops.reduce_lanes(reset=True),
                "metric": "net_flow_group_hll_rows_per_sec_per_chip",
                "value": round(n_small / best / n_chips),
                "unit": "rows/s/chip",
            }
        )

    # ---- config 6: serving concurrency soak (r12) -------------------------
    def run_config_6():
        # Concurrent scripted clients through the broker's serving path
        # (admission + shared scans + HBM residency) — the soak harness
        # as a bench config, so p50/p99, dispatch reduction, evictions,
        # and rejections land in BENCH_DETAIL.json. Opt-in via
        # BENCH_CONFIGS=...,6 (its own in-process cluster and flags; the
        # other configs' single-engine numbers are unaffected).
        sys.path.insert(0, os.path.join(REPO, "tools"))
        import soak_serving

        report = soak_serving.run_soak(
            clients=int(os.environ.get("BENCH_SOAK_CLIENTS", 64)),
            requests_per_client=int(
                os.environ.get("BENCH_SOAK_REQUESTS", 4)
            ),
            rows=int(os.environ.get("BENCH_SOAK_ROWS", 1_000_000)),
        )
        assert report["degraded"] == 0, report
        assert report["bit_identical"], "concurrent results diverged"
        assert report["residency"]["within_budget"], report["residency"]
        ledger.add(
            {
                "config": 6,
                "latency_p50_ms": report["latency_p50_ms"],
                "latency_p99_ms": report["latency_p99_ms"],
                "shared_scan": report["shared_scan"],
                "residency": report["residency"],
                "completed": report["completed"],
                "rejected": report["rejected"],
                "degraded": report["degraded"],
                "metric": "serving_concurrency_queries_per_sec",
                "value": report["queries_per_sec"],
                "unit": "queries/s",
            }
        )

    # ---- config 7: residency-aware fleet placement soak (r18) -------------
    def run_config_7():
        # 1-agent thrash baseline vs an N-agent placement-routed fleet
        # over the same hot-table workload (opt-in, BENCH_CONFIGS=...,7).
        # Records placement hit-rate, per-agent balance, and QPS-vs-
        # agent-count into BENCH_DETAIL.json's ``fleet`` block. Scaling
        # is aggregate per-agent device capacity (serialized device
        # clock in the soak harness) because the simulated chips share
        # one host core — same convention as the rows/s/chip configs.
        sys.path.insert(0, os.path.join(REPO, "tools"))
        import soak_serving

        agents = int(os.environ.get("BENCH_FLEET_AGENTS", 4))
        kw = dict(
            clients=int(os.environ.get("BENCH_FLEET_CLIENTS", 256)),
            requests_per_client=1,
            qps_per_client=50.0,
            rows=int(os.environ.get("BENCH_FLEET_ROWS", 100_000)),
            hbm_budget_mb=int(os.environ.get("BENCH_FLEET_HBM_MB", 4)),
            fleet_tables=int(os.environ.get("BENCH_FLEET_TABLES", 8)),
        )
        base = soak_serving.run_soak(agents=1, **kw)
        fleet = soak_serving.run_soak(agents=agents, **kw)
        for rep in (base, fleet):
            assert rep["degraded"] == 0, rep
            assert rep["bit_identical"], "fleet results diverged"
        pb0, pb = base["placement"], fleet["placement"]
        cap0 = pb0["device_capacity"]["aggregate_qps_capacity"]
        cap = pb["device_capacity"]["aggregate_qps_capacity"]
        scaling = round(cap / cap0, 2) if cap0 else 0.0
        assert pb["hit_rate"] >= 0.7, pb
        assert pb["balance_max_min"] <= 2.0, pb
        assert len(pb["per_agent_share"]) == agents, pb
        ledger.add(
            {
                "config": 7,
                "agents": agents,
                "placement_hit_rate": pb["hit_rate"],
                "baseline_hit_rate": pb0["hit_rate"],
                "balance_max_min": pb["balance_max_min"],
                "qps_wall": fleet["queries_per_sec"],
                "baseline_qps_capacity": cap0,
                "aggregate_qps_capacity": cap,
                "metric": "fleet_qps_capacity_scaling_x",
                "value": scaling,
                "unit": "x_vs_1_agent",
            }
        )
        # Full runs keyed by agent count (incl. the rebalancer trail)
        # merge into the ``fleet`` block AFTER the ledger flush so both
        # records land in BENCH_DETAIL.json.
        soak_serving.record_fleet_detail(base, 1)
        soak_serving.record_fleet_detail(fleet, agents)

    # ---- config 8: device sort-merge join lane (r19) ----------------------
    def run_config_8():
        # Representative telemetry equijoin: a small service→owner dim
        # table joined INNER against a fact stream on the service key.
        # Build side = left (dim), probe = right (fact) — the planner's
        # convention — so the device lane sorts 16 rows and merges the
        # fact side through searchsorted. At stock flags the lane
        # engages (4M rows ≥ device_join_min_rows, output ≤
        # device_join_max_out); join_lane records what actually ran.
        n_join = int(os.environ.get("BENCH_JOIN_ROWS", 4_000_000))
        dim_rel = Relation.of(
            ("svc", S, SemanticType.ST_SERVICE_NAME),
            ("owner", S),
        )
        td = create_table_no_ring("svc_owners", dim_rel)
        td.write_pydict(
            {
                "svc": services,
                "owner": np.array(
                    [f"team-{i % 4}" for i in range(n_services)],
                    dtype=object,
                ),
            }
        )
        td.compact()
        td.stop()
        fact_rel = Relation.of(
            ("time_", T, SemanticType.ST_TIME_NS),
            ("service", S, SemanticType.ST_SERVICE_NAME),
            ("latency", F, SemanticType.ST_DURATION_NS),
        )
        tf = create_table_no_ring("join_fact", fact_rel, size_limit=1 << 42)
        fd = tf.dictionaries["service"]
        for name in services:
            fd.get_code(name)

        def build_join_fact():
            rng = np.random.default_rng(46)
            return {
                "svc_idx": rng.integers(
                    0, n_services, n_join, dtype=np.uint8
                ),
                "latency": rng.exponential(3e7, n_join),
            }

        d8 = cache.get_or_build(f"joinfact_{n_join}_s46", build_join_fact)
        chunk = 16_000_000
        for off in range(0, n_join, chunk):
            m = min(chunk, n_join - off)
            tf.write_pydict(
                {
                    "time_": np.arange(off, off + m, dtype=np.int64) * 1000,
                    "service": DictColumn(
                        d8["svc_idx"][off : off + m].astype(np.int32), fd
                    ),
                    "latency": d8["latency"][off : off + m],
                }
            )
        tf.compact()
        tf.stop()
        q8 = (
            "l = px.DataFrame(table='svc_owners')\n"
            "r = px.DataFrame(table='join_fact')\n"
            "j = l.merge(r, how='inner', left_on=['svc'],"
            " right_on=['service'], suffixes=['', '_r'])\n"
            "px.display(j, 'joined')\n"
        )

        def verify(result) -> None:
            rows = result.table("joined")
            assert len(rows["time_"]) == n_join, len(rows["time_"])
            # Every emitted pair carries equal key columns from both
            # sides — a wrong gather/merge shows up here immediately.
            assert np.array_equal(
                np.asarray(rows["svc"], dtype=object),
                np.asarray(rows["service"], dtype=object),
            ), "join key mismatch between sides"

        result, cold8, bd = cold_run(q8)
        verify(result)
        best, last = best_of(lambda: carnot.execute_query(q8), runs)
        verify(last)
        lanes = segment_ops.reduce_lanes(reset=True)
        ledger.add(
            {
                "config": 8,
                "cold_s": cold8,
                "cold_breakdown": bd,
                "rows_per_sec": round(n_join / best),
                "reduction_lanes": lanes,
                # Always-present lane keys: a gate that silently bounced
                # the join to the host engine is a visible "host" here,
                # not a quietly slower rows/s.
                "join_lane": (
                    "device" if lanes.get("join_sort_merge") else "host"
                ),
                "join_rows_per_sec": round(n_join / best),
                "metric": "join_sort_merge_rows_per_sec_per_chip",
                "value": round(n_join / best / n_chips),
                "unit": "rows/s/chip",
            }
        )

    # ---- config 9: materialized-view dashboard soak (r20) -----------------
    def run_config_9():
        # Dashboard-repeat workload through the r20 view plane: the
        # panel scripts are registered as materialized views after the
        # serial baselines, clients re-run them, and reads merge the
        # persisted partial-agg state with a tail delta fold instead of
        # folding from scratch. The acceptance pair — view hit rate
        # >= 0.9 and fold-dispatch reduction >= 5x vs one full fold per
        # request — is asserted here and recorded in BENCH_DETAIL.json's
        # ``views`` block, with the in-run bit-identity verify (every
        # view-served read == the from-scratch baseline, and the
        # post-append delta folded via maintenance) as the correctness
        # gate. Opt-in via BENCH_CONFIGS=...,9.
        sys.path.insert(0, os.path.join(REPO, "tools"))
        import soak_serving

        report = soak_serving.run_soak(
            clients=int(os.environ.get("BENCH_VIEWS_CLIENTS", 64)),
            requests_per_client=int(
                os.environ.get("BENCH_VIEWS_REQUESTS", 4)
            ),
            rows=int(os.environ.get("BENCH_VIEWS_ROWS", 100_000)),
            views=True,
        )
        assert report["degraded"] == 0, report
        assert report["bit_identical"], "view-served reads diverged"
        vb = report["views"]
        assert vb["hit_rate"] >= 0.9, vb
        assert vb["fold_dispatch_reduction_x"] >= 5.0, vb
        assert vb["post_append_bit_identical"], vb
        ledger.add(
            {
                "config": 9,
                "view_queries": vb["queries"],
                "view_hit_rate": vb["hit_rate"],
                "view_read_p50_ms": vb["read_p50_ms"],
                "view_read_p99_ms": vb["read_p99_ms"],
                "fold_dispatches_views_on": vb["fold_dispatches_views_on"],
                "fold_dispatches_views_off": vb[
                    "fold_dispatches_views_off"
                ],
                "post_append_bit_identical": vb[
                    "post_append_bit_identical"
                ],
                "metric": "view_fold_dispatch_reduction_x",
                "value": vb["fold_dispatch_reduction_x"],
                "unit": "x_vs_views_off",
            }
        )
        # The full block (incl. the dispatch model note) merges into
        # BENCH_DETAIL.json's ``views`` key after the ledger flush.
        soak_serving.record_views_detail(report)

    # ---- config 10: multi-host mesh fold scaling (r21) --------------------
    def run_config_10():
        # Mesh-width sweep through the full engine path: every width
        # must reproduce the 1-host fold bit-exactly (asserted inside
        # the sweep), and the per-device fold rate at width 4 must stay
        # within 30% of 1-host — the r21 acceptance bar. Opt-in via
        # BENCH_CONFIGS=...,10.
        sys.path.insert(0, os.path.join(REPO, "tools"))
        import microbench_mesh

        summary = microbench_mesh.run_mesh_bench(
            rows=int(os.environ.get("BENCH_MESH_ROWS", 200_000)),
            runs=runs,
        )
        assert summary["mesh_scaling_x"] >= 0.7, summary
        ledger.add(
            {
                "config": 10,
                "mesh_widths": [e["hosts"] for e in summary["widths"]],
                "per_device_mrows_s": {
                    str(e["hosts"]): e["per_device_mrows_s"]
                    for e in summary["widths"]
                },
                "combine_overhead_pct": {
                    str(e["hosts"]): e["combine_overhead_pct"]
                    for e in summary["widths"]
                },
                # Always-present headline: a mesh regression shows up as
                # a sub-0.7 scaling number here, never a silent slowdown.
                "mesh_scaling_x": summary["mesh_scaling_x"],
                "metric": "mesh_per_device_fold_scaling_x",
                "value": summary["mesh_scaling_x"],
                "unit": "x_vs_1host_at_width_4",
            }
        )
        microbench_mesh.record_mesh_detail(summary)

    # ---- config 11: cost-model prediction accuracy (r22) ------------------
    def run_config_11():
        # Cold-vs-warmed relative prediction error of the r22 CostModel
        # over real engine dispatches; the warmed pooled p50 must stay
        # within 30% of measured wall time — the r22 acceptance bar.
        # Opt-in via BENCH_CONFIGS=...,11.
        sys.path.insert(0, os.path.join(REPO, "tools"))
        import microbench_cost_model

        summary = microbench_cost_model.run_cost_model_bench(
            rows=int(os.environ.get("BENCH_CM_ROWS", 120_000)),
            warm_runs=max(runs, 8),
        )
        assert summary["pass_p50_under_030"], summary
        ledger.add(
            {
                "config": 11,
                "cold_predictions": summary["cold"]["predictions"],
                "warmed_predictions": summary["warmed"]["predictions"],
                "warmed_p90_rel_err": summary["warmed_p90_rel_err"],
                # Always-present headline, inverted so "higher is
                # better" matches the ledger's regression gate: 1/p50
                # falling below ~3.3 means the warmed model drifted
                # past the 30% error bar.
                "warmed_p50_rel_err": summary["warmed_p50_rel_err"],
                "metric": "cost_model_warmed_p50_accuracy_x",
                "value": round(
                    1.0 / max(summary["warmed_p50_rel_err"], 1e-6), 3
                ),
                "unit": "inv_rel_err",
            }
        )
        microbench_cost_model.record_cost_model_detail(summary)

    # ---- config 12: mesh chaos recovery (r23) -----------------------------
    def run_config_12():
        # One simulated host killed mid-stream: the degraded-geometry
        # ladder must resume from the last window checkpoint
        # bit-identically, refolding only the post-checkpoint windows.
        # Records recovery seconds + refolded-window fraction under
        # BENCH_DETAIL.json's mesh_chaos block. Opt-in via
        # BENCH_CONFIGS=...,12.
        sys.path.insert(0, os.path.join(REPO, "tools"))
        import microbench_mesh

        summary = microbench_mesh.run_mesh_chaos_bench(
            rows=int(os.environ.get("BENCH_MESH_ROWS", 120_000)),
            windows=int(os.environ.get("BENCH_MESH_WINDOWS", 8)),
            runs=runs,
        )
        assert summary["bit_identical"], summary
        assert summary["restored_after_next_fold"], summary
        # Checkpoints must have saved work: a full refold means the
        # window checkpoint plane silently stopped persisting.
        assert summary["refolded_window_fraction"] < 1.0, summary
        ledger.add(
            {
                "config": 12,
                "geometry": summary["geometry"],
                "windows": summary["windows"],
                "fault_after_window": summary["fault_after_window"],
                "recovery_seconds": summary["recovery_seconds"],
                "refolded_window_fraction": summary[
                    "refolded_window_fraction"
                ],
                "degrade_events": summary["degrade_events"],
                # Always-present headline (higher is better, and
                # deterministic for a fixed window count): the stream
                # fraction the checkpoints did NOT have to refold.
                "metric": "mesh_chaos_checkpoint_saved_fraction",
                "value": summary["checkpoint_saved_fraction"],
                "unit": "fraction_of_windows",
            }
        )
        microbench_mesh.record_mesh_chaos_detail(summary)

    # ---- config 13: ingest chaos soak (r24) -------------------------------
    def run_config_13():
        # The overload-proof ingest plane under chaos: mixed-protocol
        # replay (all six parsers) through reassembly -> trackers ->
        # tables -> store with the ingest.* fault sites armed and
        # concurrent queries checked bit-identical. Records offered
        # events/s, drop fractions by reason, and the exact
        # drop-accounting invariant under BENCH_DETAIL.json's
        # ingest_soak block. Opt-in via BENCH_CONFIGS=...,13.
        sys.path.insert(0, os.path.join(REPO, "tools"))
        import soak_ingest

        report = soak_ingest.run_soak(
            duration_s=float(
                os.environ.get("BENCH_INGEST_SECONDS", 3.0)
            ),
            feeders=int(os.environ.get("BENCH_INGEST_FEEDERS", 4)),
            clients=int(os.environ.get("BENCH_INGEST_CLIENTS", 1)),
        )
        for k in (
            "law_a_exact", "law_b_exact", "law_c_exact",
            "law_push_exact",
        ):
            assert report["gates"][k], report["accounting"]
        assert report["gates"]["zero_errors"], report["errors"]
        assert report["gates"]["queries_bit_identical"], report["gates"]
        assert report["gates"]["trackers_drained"], report["gates"]
        ledger.add(
            {
                "config": 13,
                "events_offered": report["events_offered"],
                "drop_fraction": report["drop_fraction"],
                "drop_fractions_by_reason": report[
                    "drop_fractions_by_reason"
                ],
                "accounting_exact": True,
                "peak_shed_level": report["peak_shed_level"],
                "quarantine_opens": report["quarantine_opens"],
                "metric": "ingest_soak_events_per_s",
                "value": report["events_per_s"],
                "unit": "events_per_s",
            }
        )
        soak_ingest.record_ingest_soak_detail(report)

    runners = {
        "0": run_config_0,
        "1": run_config_1,
        "2": run_config_2,
        "3": run_config_3,
        "4": run_config_4,
        "5": run_config_5,
        "6": run_config_6,
        "7": run_config_7,
        "8": run_config_8,
        "9": run_config_9,
        "10": run_config_10,
        "11": run_config_11,
        "12": run_config_12,
        "13": run_config_13,
    }
    ran = set()
    for c in order:  # BENCH_CONFIGS order IS the execution order
        if c not in ran:
            ran.add(c)
            runners[c]()

    gate = ledger.gate()
    if gate["status"] == "red":
        for r in gate["regressions"]:
            log(f"PERF GATE RED: {r}")
    if not headline_printed and ledger.detail:
        headline = {
            k: v
            for k, v in ledger.detail[0].items()
            if k not in ("config", "cold_s", "cold_breakdown")
        }
        headline["gate"] = gate["status"]
        print(json.dumps(headline), flush=True)


if __name__ == "__main__":
    sys.exit(main())
